"""Centroid initialization strategies.

* ``forgy_init`` — capability parity with the reference's
  ``_initialize_centroids`` (kmeans_spark.py:58-82): sample k distinct points,
  seeded, without replacement (``rdd.takeSample(False, k, seed)``,
  kmeans_spark.py:72); raise if fewer than k points; all-finite validation.
* ``kmeanspp_init`` — beyond-reference superset: D² weighting (Arthur &
  Vassilvitskii 2007), distance updates jit-compiled on device so the O(nkD)
  work runs on the MXU; only the per-step categorical draw happens host-side.

All entry points accept either a host ``(n, D)`` array or a
``parallel.sharding.ShardedDataset`` (row access via ``.take``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kmeans_tpu.utils.validation import check_finite_array


class _EpochReservoir:
    """Seeded Algorithm-R reservoir over streamed rows: a uniform
    without-replacement sample of up to ``cap`` rows, maintained with
    O(block) vectorized host work per block.  Serves ``fit_stream``'s
    'resample' empty-cluster policy AND the streamed initializers (a
    cap-k reservoir over one full pass IS the reference's
    ``takeSample(False, k, seed)`` over the full distributed dataset,
    kmeans_spark.py:72 — r3 VERDICT #3: first-block-only seeding)."""

    def __init__(self, cap: int, d: int, rng: np.random.Generator):
        self.cap = cap
        self.rng = rng
        self.rows = np.zeros((cap, d), np.float64)
        self.seen = 0

    @property
    def filled(self) -> int:
        return min(self.seen, self.cap)

    def offer(self, block: np.ndarray) -> None:
        b = np.asarray(block, np.float64)
        nfill = max(0, min(self.cap - self.seen, len(b)))
        if nfill:
            self.rows[self.seen: self.seen + nfill] = b[:nfill]
        rest = b[nfill:]
        if len(rest):
            # Vectorized Algorithm R: row with global index t replaces a
            # reservoir slot iff randint(0, t+1) < cap.  NumPy fancy
            # assignment applies duplicates in order (last wins), which
            # reproduces the sequential algorithm exactly.
            t = self.seen + nfill + np.arange(len(rest))
            j = self.rng.integers(0, t + 1)
            hit = j < self.cap
            self.rows[j[hit]] = rest[hit]
        self.seen += len(b)

    def sample(self, m: int, rng: np.random.Generator) -> np.ndarray:
        take = min(m, self.filled)
        if take == 0:
            return np.empty((0, self.rows.shape[1]))
        idx = rng.choice(self.filled, size=take, replace=False)
        return self.rows[idx]


class _ArraySource:
    """Adapter giving a host ndarray the ShardedDataset row-access API.
    Optional ``weights`` make ``positive_rows``/``host_weights`` honor
    per-row sample weights (a zero-weight row must never seed a
    centroid)."""

    def __init__(self, X: np.ndarray, weights: Optional[np.ndarray] = None):
        self._X = np.asarray(X)
        self.n, self.d = self._X.shape
        self.dtype = self._X.dtype
        self._w = None if weights is None else np.asarray(weights)

    def take(self, idx):
        return self._X[idx]

    def positive_rows(self):
        if self._w is None:
            return np.arange(self.n)
        return np.flatnonzero(self._w > 0)

    @property
    def host(self):
        return self._X

    @property
    def host_weights(self):
        return self._w


def as_source(X, weights=None):
    if hasattr(X, "take") and hasattr(X, "n"):
        return X
    return _ArraySource(X, weights)


def forgy_init(X, k: int, seed: int, *, validate: bool = True) -> np.ndarray:
    """Seeded sample of k distinct rows (kmeans_spark.py:58-82 semantics).

    With sample weights present, sampling is uniform over the POSITIVE-
    weight rows only (a zero-weight row must never seed a centroid — it
    would start an empty cluster)."""
    src = as_source(X)
    candidates = src.positive_rows()
    if len(candidates) < k:
        raise ValueError(
            f"Not enough data points ({len(candidates)}) to initialize "
            f"{k} clusters")
    rng = np.random.RandomState(seed)
    idx = candidates[rng.choice(len(candidates), size=k, replace=False)]
    centroids = np.asarray(src.take(idx))
    # Same message as the reference's finite guard (kmeans_spark.py:79-80).
    if validate:
        check_finite_array(centroids, "Data contains NaN or Inf values")
    return centroids


@functools.partial(jax.jit, donate_argnums=(1,))
def _update_mind2(x: jax.Array, mind2: jax.Array, c: jax.Array) -> jax.Array:
    d2 = jnp.sum((x - c[None, :]) ** 2, axis=-1)
    return jnp.minimum(mind2, d2)


def _weighted_kmeanspp_host(X: np.ndarray, w: np.ndarray, k: int,
                            rng: np.random.Generator) -> np.ndarray:
    """Core weighted D²-seeding loop over a host array (device-accelerated
    distance maintenance); also the final reduction step of kmeans||."""
    n = X.shape[0]
    if int((w > 0).sum()) < k:
        raise ValueError(
            f"Not enough data points ({int((w > 0).sum())}) to initialize "
            f"{k} clusters")
    centers = np.empty((k, X.shape[1]), dtype=X.dtype)
    centers[0] = X[rng.choice(n, p=w / w.sum())]   # first draw ~ weights
    # Small arrays (every kmeans|| reduction: ~10k candidate rows) run
    # the distance maintenance in PURE numpy: the device path costs one
    # device->host transfer PER DRAW, and on a tunneled platform that
    # round trip is ~120 ms — 1023 draws made the k=1024 kmeans||
    # reduce take 126 s while the numpy loop is milliseconds (r5,
    # time-to-solution run).  Large arrays keep the device path: there
    # the O(n*d) per-draw distance update dwarfs the transfer.
    on_host = X.size <= (1 << 22)
    x = X.astype(np.float64, copy=False) if on_host else jnp.asarray(X)
    mind2 = (np.full((n,), np.inf) if on_host
             else jnp.full((n,), jnp.inf, dtype=x.dtype))
    for i in range(1, k):
        if on_host:
            diff = x - centers[i - 1].astype(np.float64)
            mind2 = np.minimum(mind2, (diff * diff).sum(axis=1))
            p = w * np.maximum(mind2, 0.0)
        else:
            mind2 = _update_mind2(x, mind2, jnp.asarray(centers[i - 1]))
            # D^2 weighting scaled by sample weights: p ~ w * mind2.
            p = w * np.maximum(np.asarray(mind2, dtype=np.float64), 0.0)
        total = p.sum()
        if not np.isfinite(total) or total <= 0:
            idx = rng.choice(n, p=w / w.sum())  # degenerate: coincident pts
        else:
            idx = rng.choice(n, p=p / total)
        centers[i] = X[idx]
    return centers


def kmeanspp_init(X, k: int, seed: int, *, validate: bool = True
                  ) -> np.ndarray:
    """k-means++ seeding; device-accelerated distance maintenance.

    ``validate=False`` skips the full-array finite scan — for callers that
    already validated the data once and re-seed repeatedly over the same
    array (e.g. BisectingKMeans' per-split 2-means fits)."""
    src = as_source(X)
    host = getattr(src, "host", None)
    if host is None:
        # Pre-sharded device-only data: run the on-device variant.
        return kmeanspp_device_init(src, k, seed)
    X = host
    sw = getattr(src, "host_weights", None)
    w = (np.ones(X.shape[0]) if sw is None
         else np.asarray(sw, dtype=np.float64))
    # Full scan (not just the chosen rows): a NaN anywhere poisons the D^2
    # distance weights, so the guard must cover all of X here.
    if validate:
        check_finite_array(X, "Data contains NaN or Inf values")
    return _weighted_kmeanspp_host(X, w, k, np.random.default_rng(seed))


@functools.partial(jax.jit, static_argnames=("k",))
def _kmeanspp_device(points: jax.Array, weights: jax.Array, k: int,
                     seed) -> jax.Array:
    """Whole k-means++ seeding in ONE dispatch, GSPMD-parallel over sharded
    points.  The categorical D²-draw uses the Gumbel-max trick — an argmax
    over (log p + gumbel noise), which XLA parallelizes across shards the
    same way every other reduction here is — so no host round-trip and no
    gather of the (n,) distance vector ever happens."""
    n, d = points.shape
    key = jax.random.PRNGKey(seed)
    neg_inf = jnp.array(-jnp.inf, points.dtype)

    w_logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-38)),
                         neg_inf)

    def draw(logits, subkey):
        g = jax.random.gumbel(subkey, (n,), dtype=points.dtype)
        # Degenerate fallback (all remaining mass zero): weight-proportional
        # over the real rows.
        logits = jnp.where(jnp.any(jnp.isfinite(logits)), logits, w_logits)
        return jnp.argmax(logits + g)

    idx0 = draw(w_logits, jax.random.fold_in(key, 0))  # first ~ weights
    centers0 = jnp.zeros((k, d), points.dtype).at[0].set(points[idx0])
    mind20 = jnp.full((n,), jnp.inf, points.dtype)

    def body(i, carry):
        centers, mind2 = carry
        c = centers[i - 1]
        d2 = jnp.sum((points - c[None, :]) ** 2, axis=1)
        mind2 = jnp.minimum(mind2, d2)
        p = weights * mind2                 # D^2 x sample-weight mass
        logits = jnp.where(p > 0, jnp.log(p), neg_inf)
        idx = draw(logits, jax.random.fold_in(key, i))
        return centers.at[i].set(points[idx]), mind2

    centers, _ = jax.lax.fori_loop(1, k, body, (centers0, mind20))
    return centers


def kmeanspp_device_init(ds, k: int, seed: int) -> np.ndarray:
    """k-means++ on a ShardedDataset — fully on-device (see
    ``_kmeanspp_device``); used automatically when no host copy exists."""
    if ds.n < k:
        raise ValueError(
            f"Not enough data points ({ds.n}) to initialize {k} clusters")
    centers = np.asarray(_kmeanspp_device(ds.points, ds.weights, k, seed))
    check_finite_array(centers, "Data contains NaN or Inf values")
    return centers


@functools.partial(jax.jit, static_argnames=("cap",))
def _parallel_round(weights, mind2, phi, key, ell, cap: int):
    """One kmeans|| oversampling round, fully on device: Bernoulli-sample
    each point with prob min(1, ell*w*d²/phi); returns up to ``cap`` sampled
    indices plus a validity mask.  The caller is responsible for folding the
    returned candidates into ``mind2`` before the next round."""
    p = jnp.minimum(1.0, ell * weights * mind2 /
                    jnp.maximum(phi, jnp.finfo(mind2.dtype).tiny))
    u = jax.random.uniform(key, mind2.shape, dtype=mind2.dtype)
    sampled = (u < p) & (weights > 0)
    # Up to cap winners; among sampled points the u-order is an arbitrary
    # (seed-determined) subset, which is what the cap needs.
    score = jnp.where(sampled, 1.0 + u, 0.0)
    vals, idx = jax.lax.top_k(score, cap)
    return idx, vals > 0


@functools.partial(jax.jit, donate_argnums=(1,))
def _fold_candidates(points, mind2, cands, valid):
    """mind2 <- min(mind2, d²(points, c)) over all valid candidate rows,
    as ONE chunked matmul-form distance pass.

    r5 rewrite: the original scanned candidates one at a time, each step
    broadcasting (points - c)² over the full array — a re-read of the
    whole dataset PER CANDIDATE (10.5 TB of HBM traffic per round at
    10M x 128 with the 2048-candidate cap; measured 348 s of k-means||
    init in the time-to-solution run).  The matmul form reads points
    once per round and puts the distance work on the MXU.  Invalid
    candidate rows get ``+inf`` squared norms, so they can never win the
    min — same semantics as the masked scan."""
    from kmeans_tpu.ops.assign import pairwise_sq_dists

    n, d = points.shape
    cap = cands.shape[0]
    # (chunk, cap) distance tile bounded at 2^23 elems; cap treated as
    # >= 64 so a 1-candidate fold doesn't slice GB-scale windows.
    chunk = int(min(n, max(128, (1 << 23) // max(cap, 64) // 8 * 8)))
    n_chunks = -(-n // chunk)

    def body(i, m):
        # Clamped sliding window: the last window may overlap the
        # previous one — min is idempotent, re-minning rows is free.
        start = jnp.minimum(i * chunk, n - chunk)
        zero = jnp.zeros((), start.dtype)
        xc = jax.lax.dynamic_slice(points, (start, zero), (chunk, d))
        mc = jax.lax.dynamic_slice(m, (start,), (chunk,))
        # HIGHEST cross-term: the fold's answer is the distance VALUE —
        # a covered point must read ~0, and bf16-rounded products would
        # leave it |x||c|*2^-8 of sampling mass (see pairwise_sq_dists).
        d2 = pairwise_sq_dists(xc, cands,
                               precision=jax.lax.Precision.HIGHEST)
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
        # pairwise_sq_dists accumulates in at least f32; cast back so
        # float16 mind2 buffers round-trip (r5 review).
        best = jnp.minimum(mc, jnp.min(d2, axis=1).astype(m.dtype))
        return jax.lax.dynamic_update_slice(m, best, (start,))

    return jax.lax.fori_loop(0, n_chunks, body, mind2)


def kmeans_parallel_init(X, k: int, seed: int, *, rounds: int = 5,
                         oversampling: Optional[float] = None,
                         validate: bool = True) -> np.ndarray:
    """kmeans|| seeding (Bahmani et al. 2012) — the distributed-scale
    initializer.  Each round Bernoulli-samples ~l = oversampling*k
    candidates proportional to current D² cost, fully on device over the
    sharded points; candidates are then weighted by the size of their
    nearest-candidate cell (ONE fused assign_reduce pass) and reduced to k
    seeds with weighted k-means++ on the host.  O(rounds) passes over the
    data instead of k-means++'s O(k)."""
    from kmeans_tpu.ops.assign import assign_reduce

    src = as_source(X)
    candidates_idx = src.positive_rows()
    if len(candidates_idx) < k:
        raise ValueError(
            f"Not enough data points ({len(candidates_idx)}) to initialize "
            f"{k} clusters")
    if validate and getattr(src, "host", None) is not None:
        check_finite_array(src.host, "Data contains NaN or Inf values")

    points = getattr(src, "points", None)
    weights = getattr(src, "weights", None)
    if points is None:                   # plain host array source
        points = jnp.asarray(src.host)
        weights = (jnp.ones(src.n, points.dtype)
                   if src.host_weights is None
                   else jnp.asarray(src.host_weights, points.dtype))

    ell = float(oversampling if oversampling is not None else 2 * k)
    # cap may not exceed the (padded) point count — lax.top_k requires it.
    cap = int(min(max(2 * k, 256), 2048, points.shape[0]))
    rounds = max(rounds, -(-int(1.5 * k) // cap))  # ensure >= 1.5k samples
    key = jax.random.PRNGKey(seed)
    rng = np.random.default_rng(seed)

    # Seed candidate: one weight-proportional draw (matching the first draw
    # of _weighted_kmeanspp_host / _kmeanspp_device).
    sw = getattr(src, "host_weights", None)
    if sw is None:
        first = int(candidates_idx[rng.integers(len(candidates_idx))])
    else:
        pw = np.asarray(sw, dtype=np.float64)[candidates_idx]
        first = int(candidates_idx[rng.choice(len(candidates_idx),
                                              p=pw / pw.sum())])
    cand_rows = [np.asarray(src.take(np.array([first])))]
    cand_valid = [np.ones(1, bool)]
    mind2 = jnp.full((points.shape[0],), jnp.inf, points.dtype)
    mind2 = _fold_candidates(points, mind2,
                             jnp.asarray(cand_rows[0]),
                             jnp.ones(1, bool))

    for r in range(rounds):
        phi = jnp.sum(jnp.where(weights > 0, mind2 * weights, 0.0))
        idx, valid = _parallel_round(weights, mind2, phi,
                                     jax.random.fold_in(key, r), ell, cap)
        rows_dev = points[idx]                # gather stays on device
        cand_rows.append(np.asarray(rows_dev))
        cand_valid.append(np.asarray(valid))
        mind2 = _fold_candidates(points, mind2, rows_dev, valid)

    cands = np.concatenate(cand_rows)[np.concatenate(cand_valid)]
    cands = np.unique(cands, axis=0)
    if len(cands) < k:                       # tiny data: backfill uniformly
        extra = src.take(candidates_idx[rng.choice(
            len(candidates_idx), size=k - len(cands), replace=False)])
        cands = np.concatenate([cands, np.asarray(extra)])

    # Weight candidates by their nearest-candidate cell mass: one fused
    # pass of the SAME step kernel with candidates as "centroids".
    # Chunk by the shared budget rule — the old hardcoded 512 meant a
    # ~19,500-step scan at the 10M headline (r5).
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    chunk = choose_chunk_size(points.shape[0], len(cands), points.shape[1])
    pad = (-points.shape[0]) % chunk
    pts_pad = jnp.pad(points, ((0, pad), (0, 0)))
    w_pad = jnp.pad(weights, (0, pad))
    stats = assign_reduce(pts_pad, w_pad, jnp.asarray(cands),
                          chunk_size=chunk)
    cell_mass = np.maximum(np.asarray(stats.counts, np.float64), 1e-12)

    centers = _weighted_kmeanspp_host(cands.astype(np.float64), cell_mass,
                                      k, rng)
    return centers.astype(np.asarray(cands).dtype)


# ------------------------------------------------------------- streaming
# fit_stream initializers: the dataset is only ever seen block-at-a-time,
# so named strategies get streamed equivalents that draw over the FULL
# stream instead of its first block (r3 VERDICT #3; the reference's
# takeSample draws over the whole distributed dataset, kmeans_spark.py:72).
# All take a ``seeds`` LIST and share each data pass across restarts, so
# n_init=R costs R x compute but only 1x IO per pass.  Stream items may
# be bare (m, D) blocks or (block, weights) tuples (r4: weighted
# streams) — ``_split_block`` is the single decoder.


def _block_of(item):
    """Block part of a stream item, for inference paths that don't
    consume weights (predict/transform/score streams) — validates the
    tuple arity like ``_split_block`` but drops the weights."""
    if isinstance(item, tuple):
        if len(item) != 2:
            raise ValueError(
                f"stream items must be (m, D) blocks or (block, weights) "
                f"pairs, got a {len(item)}-tuple")
        return item[0]
    return item


def _split_block(item, d: int, dtype):
    """Decode one stream item: a bare (m, D) array or a (block, weights)
    tuple.  Returns (block contiguous in ``dtype``, weights (m,) in the
    block dtype or None), with the same validation every consumer needs."""
    if isinstance(item, tuple):
        if len(item) != 2:
            raise ValueError(
                f"stream items must be (m, D) blocks or (block, weights) "
                f"pairs, got a {len(item)}-tuple")
        block, w = item
    else:
        block, w = item, None
    block = np.ascontiguousarray(np.asarray(block, dtype=dtype))
    if block.ndim != 2 or block.shape[1] != d:
        raise ValueError(f"block shape {block.shape} != (*, {d})")
    if w is not None:
        # The SAME validation the in-memory sample_weight path applies
        # (shape, finiteness, non-negativity) — one rule, two engines.
        from kmeans_tpu.parallel.sharding import _validate_sample_weight
        w = _validate_sample_weight(w, block.shape[0], block.dtype)
    return block, w


def _reservoir_pass(make_blocks, cap: int, k: int, d: int, seeds,
                    salt: int):
    """Shared single-pass scaffold of the streamed samplers: one seeded
    cap-row Algorithm-R reservoir per restart over the POSITIVE-weight
    rows of the whole stream (the in-memory ``forgy_init`` weight rule).
    Raises the standard n<k error.  Returns (reservoirs, n_rows)."""
    from kmeans_tpu.data.prefetch import close_source
    res = [_EpochReservoir(cap, d, np.random.default_rng([s, salt]))
           for s in seeds]
    n = 0
    # close_source in finally: a decode error mid-pass must reap a
    # prefetching source's producer thread, not leave it to cyclic GC.
    it = iter(make_blocks())
    try:
        for item in it:
            block, bw = _split_block(item, d, np.float64)
            b = block if bw is None else block[bw > 0]
            n += len(b)
            for r in res:
                r.offer(b)
    finally:
        close_source(it)
    if n < k:
        raise ValueError(
            f"Not enough data points ({n}) to initialize {k} clusters")
    return res, n


def streamed_forgy_init(make_blocks, k: int, seeds, d: int, dtype):
    """ONE pass: per-seed cap-k Algorithm-R reservoirs — each result is a
    uniform without-replacement k-row sample of the whole stream, the
    exact capability of ``rdd.takeSample(False, k, seed)``
    (kmeans_spark.py:72).  Weighted streams draw uniformly over the
    POSITIVE-weight rows, the in-memory ``forgy_init`` rule.  Returns
    (list of (k, d) arrays, n_total)."""
    res, n = _reservoir_pass(make_blocks, k, k, d, seeds, 0xF0261)
    outs = []
    for r in res:
        c = r.rows[: r.filled].astype(dtype)
        check_finite_array(c, "Data contains NaN or Inf values")
        outs.append(c)
    return outs, n


def streamed_init_sample(make_blocks, k: int, seeds, d: int, dtype, *,
                         cap: Optional[int] = None):
    """ONE pass: per-seed uniform reservoir samples of the WHOLE stream
    for CALLABLE inits (r4 VERDICT #8 — callables previously saw only
    the first block, while every built-in streamed init draws over the
    full stream like the reference's ``takeSample`` over the whole
    distributed dataset, kmeans_spark.py:72).

    Each result is a uniform without-replacement sample of up to ``cap``
    positive-weight rows (Algorithm R), in randomly-permuted order —
    enough for a D²-weighting or subsample-then-solve callable to be
    meaningful, while bounding host memory (``cap`` defaults to
    ``clamp(16*k, 2048, 32768)`` and is floored to ``k`` so the sample
    can always seed k centroids).  Returns (list of (m, d) ``dtype``
    arrays, n_total)."""
    cap = int(cap if cap is not None else min(max(16 * k, 2048), 32768))
    cap = max(cap, k)
    res, n = _reservoir_pass(make_blocks, cap, k, d, seeds, 0xCA11AB1E)
    outs = []
    for r, s in zip(res, seeds):
        # The reservoir's slot order is fill-order-biased (early rows sit
        # in early slots); permute so positional callables (e.g.
        # ``lambda X, k, seed: X[:k]``) still get a uniform draw.
        rows = r.rows[: r.filled]
        perm = np.random.default_rng([s, 0x5EED]).permutation(len(rows))
        c = rows[perm].astype(dtype)
        check_finite_array(c, "Data contains NaN or Inf values")
        outs.append(c)
    return outs, n


@functools.partial(jax.jit, static_argnames=("cap",))
def _stream_round_block(x, w, cands, phi_prev, ell, key, cap: int):
    """One block's contribution to one streamed kmeans|| round: min
    squared distance to the CURRENT candidate set (matmul form on the
    MXU), Bernoulli-sample rows w.p. ``min(1, ell*w*d2/phi_prev)``,
    return up to ``cap`` sampled rows + validity + this block's weighted
    cost (which accumulates into the NEXT round's phi).  ``w`` carries
    the per-row sample weights folded into the 0/1 padding mask —
    blocks arrive padded to a fixed row multiple so ragged streams
    compile once per round, not once per block length; unweighted
    streams pass the bare mask (w=1 on real rows)."""
    from kmeans_tpu.ops.assign import pairwise_sq_dists
    # HIGHEST cross-term for the same reason as _fold_candidates: the
    # D^2 VALUE is the sampling mass, and bf16 products would leave
    # covered rows |x||c|*2^-8 instead of ~0.
    d2 = jnp.maximum(
        jnp.min(pairwise_sq_dists(x, cands, mode="matmul",
                                  precision=jax.lax.Precision.HIGHEST),
                axis=1), 0.0)
    d2w = d2 * w                                   # weighted D^2 mass;
    phi_b = jnp.sum(d2w)                           # padding rows: 0
    p = jnp.minimum(1.0, ell * d2w /
                    jnp.maximum(phi_prev, jnp.finfo(d2w.dtype).tiny))
    u = jax.random.uniform(key, d2w.shape, d2w.dtype)
    score = jnp.where((u < p) & (w > 0), 1.0 + u, 0.0)
    vals, idx = jax.lax.top_k(score, cap)
    return x[idx], vals > 0, phi_b


def streamed_kmeans_parallel_init(make_blocks, k: int, seeds, d: int,
                                  dtype, *, rounds: int = 5,
                                  oversampling: Optional[float] = None):
    """Streamed kmeans|| (Bahmani et al. 2012) over a block stream.

    Differences from the in-memory ``kmeans_parallel_init``, forced by
    the one-block-at-a-time access pattern and documented here:

    * ``phi`` for round r's sampling is the cost accumulated during
      round r-1's pass (one candidate-set stale — the true phi would
      need an extra pass per round).  A stale phi only LOWERS sampling
      probability slightly; kmeans|| is robust to the oversampling
      factor.
    * The first candidate comes from a cap-1 reservoir pass (uniform
      over the stream), and backfill rows (when dedup'd candidates < k)
      from a cap-k reservoir maintained during the cell-mass pass.

    Passes over the stream: 1 (reservoir) + 1 (initial phi) + rounds
    (sampling) + 1 (cell mass) — one-time init cost comparable to
    ``rounds + 3`` Lloyd iterations.  Returns (list of (k, d) arrays,
    n_total)."""
    from kmeans_tpu.ops.assign import assign_reduce

    R = len(seeds)
    ell = float(oversampling if oversampling is not None else 2 * k)
    cap = int(min(max(2 * k, 256), 2048))
    res = [_EpochReservoir(1, d, np.random.default_rng([s, 0xF1257]))
           for s in seeds]
    from kmeans_tpu.data.prefetch import close_source
    n = 0
    it = iter(make_blocks())                         # pass: first cand + n
    try:
        for item in it:
            block, bw = _split_block(item, d, np.float64)
            b = block if bw is None else block[bw > 0]
            n += len(b)
            for r in res:
                r.offer(b)
    finally:
        close_source(it)
    if n < k:
        raise ValueError(
            f"Not enough data points ({n}) to initialize {k} clusters")
    cands = [r.rows[:1].copy() for r in res]         # per-seed candidates

    def epoch_blocks():
        """Blocks padded to a fixed row multiple (>= cap, so top_k's
        static argument is always just ``cap``): ragged streams compile
        one program per round instead of one per block length.  Sample
        weights fold into the padding mask, making every downstream
        reduction weighted."""
        from kmeans_tpu.parallel.sharding import pad_points
        mult = -(-cap // 512) * 512      # >= cap AND a 512-chunk multiple
        it = iter(make_blocks())
        try:
            for item in it:
                block, bw = _split_block(item, d, dtype)
                x, w = pad_points(block, mult)
                if bw is not None:
                    w[: block.shape[0]] *= bw.astype(w.dtype)
                yield x, w
        finally:
            close_source(it)

    phi = np.zeros(R)
    for x, w in epoch_blocks():                      # pass: initial phi
        xd, wd = jnp.asarray(x), jnp.asarray(w)
        for r in range(R):
            _, _, phi_b = _stream_round_block(
                xd, wd, jnp.asarray(cands[r].astype(dtype)), jnp.inf,
                0.0, jax.random.PRNGKey(0), cap)
            phi[r] += float(phi_b)

    keys = [jax.random.PRNGKey(
        int(np.random.SeedSequence([s, 0xF1258]).generate_state(1)[0]
            % (2 ** 31))) for s in seeds]
    for rd in range(rounds):                         # sampling passes
        new = [[] for _ in range(R)]
        phi_next = np.zeros(R)
        for bi, (x, w) in enumerate(epoch_blocks()):
            xd, wd = jnp.asarray(x), jnp.asarray(w)
            for r in range(R):
                rows, valid, phi_b = _stream_round_block(
                    xd, wd, jnp.asarray(cands[r].astype(dtype)),
                    float(phi[r]), ell,
                    jax.random.fold_in(
                        jax.random.fold_in(keys[r], rd), bi), cap)
                rows, valid = np.asarray(rows), np.asarray(valid)
                if valid.any():
                    new[r].append(rows[valid].astype(np.float64))
                phi_next[r] += float(phi_b)
        for r in range(R):
            if new[r]:
                cands[r] = np.concatenate([cands[r]] + new[r])
        phi = phi_next

    for r in range(R):
        cands[r] = np.unique(cands[r], axis=0)

    # Cell-mass pass (+ cap-k backfill reservoirs, maintained only for
    # restarts that actually came up short — review r4).
    masses = [np.zeros(len(c)) for c in cands]
    short = [r for r in range(R) if len(cands[r]) < k]
    back = {r: _EpochReservoir(k, d,
                               np.random.default_rng([seeds[r], 0xF1259]))
            for r in short}
    chunk = 512
    for x, w in epoch_blocks():
        xp, wp = jnp.asarray(x), jnp.asarray(w)
        for r in range(R):
            st = assign_reduce(xp, wp, jnp.asarray(cands[r].astype(dtype)),
                               chunk_size=chunk)
            masses[r] += np.asarray(st.counts, np.float64)
        if short:
            real = x[np.asarray(w) > 0]
            for r in short:
                back[r].offer(real)

    outs = []
    for r in range(R):
        c = cands[r]
        if len(c) < k:
            extra = back[r].sample(
                k - len(c), np.random.default_rng([seeds[r], 0xF1260]))
            c = np.concatenate([c, extra])
            masses[r] = np.concatenate(
                [masses[r], np.ones(len(extra))])
        centers = _weighted_kmeanspp_host(
            c.astype(np.float64), np.maximum(masses[r][: len(c)], 1e-12),
            k, np.random.default_rng(seeds[r]))
        centers = centers.astype(dtype)
        check_finite_array(centers, "Data contains NaN or Inf values")
        outs.append(centers)
    return outs, n


STREAM_INITIALIZERS = {"forgy": streamed_forgy_init,
                       "random": streamed_forgy_init,
                       "k-means++": streamed_kmeans_parallel_init,
                       "kmeans++": streamed_kmeans_parallel_init,
                       "k-means||": streamed_kmeans_parallel_init,
                       "kmeans||": streamed_kmeans_parallel_init}


INITIALIZERS = {"forgy": forgy_init, "random": forgy_init,
                "k-means++": kmeanspp_init, "kmeans++": kmeanspp_init,
                "k-means||": kmeans_parallel_init,
                "kmeans||": kmeans_parallel_init}


def resolve_init(init, X, k: int, seed: int, *,
                 validate: bool = True) -> np.ndarray:
    """Dispatch: strategy name, callable, or an explicit (k, D) array.

    ``validate=False`` skips redundant full-array finite scans in the named
    strategies (data already validated by the caller); custom callables
    manage their own validation."""
    src = as_source(X)
    dtype = np.dtype(str(src.dtype))
    if callable(init):
        host = getattr(src, "host", None)
        return np.asarray(init(host if host is not None else src, k, seed),
                          dtype=dtype)
    if isinstance(init, str):
        try:
            fn = INITIALIZERS[init]
        except KeyError:
            raise ValueError(f"unknown init strategy: {init!r}; "
                             f"options: {sorted(INITIALIZERS)}") from None
        return np.asarray(fn(src, k, seed, validate=validate), dtype=dtype)
    arr = np.asarray(init, dtype=dtype)
    if arr.shape != (k, src.d):
        raise ValueError(f"explicit init must have shape ({k}, "
                         f"{src.d}), got {arr.shape}")
    check_finite_array(arr, "Data contains NaN or Inf values")
    return arr
