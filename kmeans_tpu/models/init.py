"""Centroid initialization strategies.

* ``forgy_init`` — capability parity with the reference's
  ``_initialize_centroids`` (kmeans_spark.py:58-82): sample k distinct points,
  seeded, without replacement (``rdd.takeSample(False, k, seed)``,
  kmeans_spark.py:72); raise if fewer than k points; all-finite validation.
* ``kmeanspp_init`` — beyond-reference superset: D² weighting (Arthur &
  Vassilvitskii 2007), distance updates jit-compiled on device so the O(nkD)
  work runs on the MXU; only the per-step categorical draw happens host-side.

All entry points accept either a host ``(n, D)`` array or a
``parallel.sharding.ShardedDataset`` (row access via ``.take``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from kmeans_tpu.utils.validation import check_finite_array


class _ArraySource:
    """Adapter giving a host ndarray the ShardedDataset row-access API."""

    def __init__(self, X: np.ndarray):
        self._X = np.asarray(X)
        self.n, self.d = self._X.shape
        self.dtype = self._X.dtype

    def take(self, idx):
        return self._X[idx]

    @property
    def host(self):
        return self._X


def as_source(X):
    return X if hasattr(X, "take") and hasattr(X, "n") else _ArraySource(X)


def forgy_init(X, k: int, seed: int) -> np.ndarray:
    """Seeded sample of k distinct rows (kmeans_spark.py:58-82 semantics)."""
    src = as_source(X)
    if src.n < k:
        raise ValueError(
            f"Not enough data points ({src.n}) to initialize {k} clusters")
    rng = np.random.RandomState(seed)
    idx = rng.choice(src.n, size=k, replace=False)
    centroids = np.asarray(src.take(idx))
    # Same message as the reference's finite guard (kmeans_spark.py:79-80).
    check_finite_array(centroids, "Data contains NaN or Inf values")
    return centroids


@functools.partial(jax.jit, donate_argnums=(1,))
def _update_mind2(x: jax.Array, mind2: jax.Array, c: jax.Array) -> jax.Array:
    d2 = jnp.sum((x - c[None, :]) ** 2, axis=-1)
    return jnp.minimum(mind2, d2)


def kmeanspp_init(X, k: int, seed: int) -> np.ndarray:
    """k-means++ seeding; device-accelerated distance maintenance."""
    src = as_source(X)
    host = getattr(src, "host", None)
    if host is None:
        raise ValueError("k-means++ init requires host data; pass a NumPy "
                         "array (not a pre-sharded ShardedDataset)")
    X = host
    n = X.shape[0]
    if n < k:
        raise ValueError(
            f"Not enough data points ({n}) to initialize {k} clusters")
    # Full scan (not just the chosen rows): a NaN anywhere poisons the D^2
    # distance weights, so the guard must cover all of X here.
    check_finite_array(X, "Data contains NaN or Inf values")
    rng = np.random.default_rng(seed)
    x = jnp.asarray(X)
    centers = np.empty((k, X.shape[1]), dtype=X.dtype)
    centers[0] = X[rng.integers(n)]
    mind2 = jnp.full((n,), jnp.inf, dtype=x.dtype)
    for i in range(1, k):
        mind2 = _update_mind2(x, mind2, jnp.asarray(centers[i - 1]))
        p = np.asarray(mind2, dtype=np.float64)
        p = np.maximum(p, 0.0)
        total = p.sum()
        if not np.isfinite(total) or total <= 0:
            idx = rng.integers(n)           # degenerate: all points coincide
        else:
            idx = rng.choice(n, p=p / total)
        centers[i] = X[idx]
    return centers


INITIALIZERS = {"forgy": forgy_init, "random": forgy_init,
                "k-means++": kmeanspp_init, "kmeans++": kmeanspp_init}


def resolve_init(init, X, k: int, seed: int) -> np.ndarray:
    """Dispatch: strategy name, callable, or an explicit (k, D) array."""
    src = as_source(X)
    dtype = np.dtype(str(src.dtype))
    if callable(init):
        host = getattr(src, "host", None)
        return np.asarray(init(host if host is not None else src, k, seed),
                          dtype=dtype)
    if isinstance(init, str):
        try:
            fn = INITIALIZERS[init]
        except KeyError:
            raise ValueError(f"unknown init strategy: {init!r}; "
                             f"options: {sorted(INITIALIZERS)}") from None
        return np.asarray(fn(src, k, seed), dtype=dtype)
    arr = np.asarray(init, dtype=dtype)
    if arr.shape != (k, src.d):
        raise ValueError(f"explicit init must have shape ({k}, "
                         f"{src.d}), got {arr.shape}")
    check_finite_array(arr, "Data contains NaN or Inf values")
    return arr
