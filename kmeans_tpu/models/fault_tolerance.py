"""Shared auto-checkpointing + elastic-recovery plumbing for every
model family (ISSUE 4 + ISSUE 5).

One mixin carries the pieces every fault-tolerant fit needs:

* ``_check_ckpt`` — knob validation (``checkpoint_every``/``_path``
  pairing, n_init=1 — a restart sweep re-initializes, so a partial
  sweep has no well-defined resume point); also records the ACTIVE
  checkpoint path for the divergence-rollback machinery and resets the
  per-fit recovery counters (``oom_backoffs_``/``effective_chunk_``);
* ``_write_autockpt`` — the rotating atomic write
  (``utils.checkpoint.save_state_rotating`` under the multi-host
  primary-gated barrier) followed by the deterministic fault-injection
  boundary hook (``utils.faults.on_checkpoint``) — fired only AFTER the
  checkpoint is durable, so an injected kill always leaves a valid
  resume point;
* ``_resolve_resume`` — ``resume`` may be a checkpoint PATH: load it
  (falling back to the last-good ``.prev`` rotation with a warning on
  corruption), sanity-check the model class / cluster count, restore
  the fitted state, and continue as ``resume=True``.  State is
  CANONICAL (unsharded, topology-independent — see
  ``utils.checkpoint``), so the resuming model may sit on a different
  mesh size or TP layout than the writer (ISSUE 5 elasticity);
* ``_dispatch_oom_safe`` — the OOM-graceful segment dispatcher: a
  ``RESOURCE_EXHAUSTED``/``XlaRuntimeError`` from a device-loop
  segment halves the effective scan chunk (largest committed-chunk
  divisor, floored at ``sharding.MIN_CHUNK`` — the same divisor rule
  as ``clamp_chunk_for_k``), re-builds the step fn, and replays the
  segment from the boundary state (== the last checkpoint); bounded
  attempts, ``oom_backoffs_``/``effective_chunk_`` observability, and
  an injection point (``faults.on_segment_dispatch``) INSIDE the try
  block so the recovery is proven through the real code path;
* ``_raise_divergence`` — the divergence-rollback exit: on a
  non-finite trajectory the fitted state is rolled back to the
  last-good checkpoint (when one is active and loads) before
  :class:`NumericalDivergenceError` — naming the iteration and the
  offending quantity — propagates, so a diverged long fit keeps its
  last healthy state instead of losing everything to a post-hoc NaN
  error.

Host classes provide ``_state_dict()`` / ``_restore_state(state)`` (the
same pair ``save``/``load`` use) and declare ``_ckpt_k_attr`` — the
cluster-count constructor attribute ('k' for the K-Means families,
'n_components' for the mixture) checked against the checkpoint.
"""

from __future__ import annotations

import os

from kmeans_tpu.obs import memory as obs_memory
from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs import note_progress as obs_note_progress
from kmeans_tpu.utils import checkpoint as ckpt
from kmeans_tpu.utils import faults


class NumericalDivergenceError(ValueError):
    """The fit's trajectory went non-finite (ISSUE 5).  Carries
    ``iteration`` (the first diverged iteration), ``quantity``
    ('centroids' | 'log-likelihood' | 'covariance'),
    ``rolled_back_to`` (the iteration of the last-good checkpoint the
    model was restored to, None when no checkpoint was active), and
    ``checkpoint_path``.  A ``ValueError`` subclass whose message keeps
    the historical phrasing ("NaN or Inf detected in centroids…" /
    "non-finite log-likelihood…"), so existing handlers keep working.
    """

    _PHRASE = {
        "centroids": "NaN or Inf detected in centroids at iteration {i}",
        "log-likelihood": "non-finite log-likelihood at EM iteration {i}",
        "covariance": "ill-defined empirical covariance at EM "
                      "iteration {i}",
    }

    def __init__(self, quantity: str, iteration: int, *,
                 rolled_back_to=None, checkpoint_path=None, detail=""):
        self.quantity = quantity
        self.iteration = int(iteration)
        self.rolled_back_to = rolled_back_to
        self.checkpoint_path = checkpoint_path
        msg = self._PHRASE.get(quantity,
                               f"non-finite {quantity} at iteration "
                               "{i}").format(i=iteration)
        if detail:
            msg += f" ({detail})"
        if rolled_back_to is not None:
            msg += (f"; fitted state rolled back to the last-good "
                    f"checkpoint (iteration {rolled_back_to}, "
                    f"{checkpoint_path}) — inspect, adjust, and continue "
                    f"with fit(resume=<path>)")
        elif checkpoint_path is not None:
            msg += (f"; the last-good checkpoint at {checkpoint_path} "
                    f"could not be restored")
        super().__init__(msg)


#: Message tags XLA uses for device memory exhaustion (the
#: ``XlaRuntimeError`` classification surface; jaxlib has no stable
#: exception subclass per status code): the RESOURCE_EXHAUSTED status
#: name and the allocator's "out of memory" phrase.  Deliberately NO
#: bare "OOM" substring — an unrelated runtime error merely mentioning
#: it must not be absorbed into 12 chunk-halving replays (review r10).
#: ``faults.SimulatedOOM`` carries the first tag so injection
#: exercises this exact test.
_OOM_TAGS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")

#: Bounded backoff: more halvings than any real chunk ladder needs
#: (2^17 -> 128 is 10 steps), small enough that a persistent
#: non-memory RESOURCE_EXHAUSTED cannot loop long.
MAX_OOM_BACKOFFS = 12


def is_oom_error(e: BaseException) -> bool:
    """True when ``e`` is a device memory-exhaustion failure worth
    retrying at a smaller chunk: an ``XlaRuntimeError`` (or any
    ``RuntimeError``, covering the injected :class:`SimulatedOOM`)
    whose message carries one of XLA's OOM tags.  Preemptions
    (:class:`faults.SimulatedPreemption`) are explicitly excluded —
    they must propagate, never be absorbed by a retry loop."""
    if isinstance(e, faults.SimulatedPreemption):
        return False
    if not isinstance(e, (RuntimeError, MemoryError)):
        return False
    return any(tag in str(e) for tag in _OOM_TAGS)


class AutoCheckpointMixin:

    _ckpt_k_attr = "k"

    def _check_ckpt(self, checkpoint_every, checkpoint_path) -> int:
        """Validate the auto-checkpoint knobs (shared by every family's
        fit/fit_stream).  Also the per-fit reset point for the elastic
        recovery machinery: records the active checkpoint path (what
        ``_raise_divergence`` rolls back to) and zeroes the
        ``oom_backoffs_``/``effective_chunk_`` observability attrs."""
        n = int(checkpoint_every)
        if n < 0 or n != checkpoint_every:
            raise ValueError(f"checkpoint_every must be an int >= 0, got "
                             f"{checkpoint_every!r}")
        if n > 0 and checkpoint_path is None:
            raise ValueError("checkpoint_every > 0 requires "
                             "checkpoint_path")
        if n == 0 and checkpoint_path is not None:
            raise ValueError("checkpoint_path requires "
                             "checkpoint_every >= 1")
        if n > 0 and self.n_init != 1:
            raise ValueError(
                "auto-checkpointing (checkpoint_every > 0) requires "
                "n_init == 1: a restart sweep re-initializes, so a "
                "partially-swept fit has no well-defined resume point")
        self._active_ckpt_path = checkpoint_path if n > 0 else None
        if n > 0:
            # AOT artifact shipping (ISSUE 15a): with an executable
            # store active, everything this fit compiles is mirrored
            # into the checkpoint's sibling ``<path>.aot`` directory —
            # so state + executables travel together and an elastic
            # restart on a fresh host skips the compile column.  A
            # no-op without a store.
            from kmeans_tpu.utils import aot as _aot
            _aot.on_checkpoint_path(checkpoint_path)
        # Rollback is only legal once THIS fit has a stake in the path:
        # a checkpoint it wrote, or the state it resumed from.  Without
        # this, a diverging fit that reuses a path from an earlier,
        # unrelated fit would silently restore that fit's stale state
        # (review r10).
        self._ckpt_written_this_fit = False
        self.oom_backoffs_ = 0
        self.effective_chunk_ = None
        return n

    def _ckpt_meta(self) -> dict:
        """The topology metadata block stamped into every checkpoint
        (``utils.checkpoint.topology_meta``): mesh shape / TP layout
        written on, jax version, dtype, format version."""
        return ckpt.topology_meta(
            mesh=getattr(self, "mesh", None),
            model_shards=getattr(self, "model_shards", None),
            dtype=getattr(self, "dtype", None))

    def _dispatch_oom_safe(self, dispatch, chunk: int, segment: int):
        """Run ``dispatch(chunk)`` with OOM-graceful degradation
        (ISSUE 5): a ``RESOURCE_EXHAUSTED`` device failure halves the
        effective chunk to the largest committed-chunk divisor
        (``sharding.backoff_chunk``, floored at ``MIN_CHUNK``),
        re-builds the step fn (the caller's ``dispatch`` closure keys
        its compile cache by chunk), and replays the segment from the
        boundary state — which IS the last checkpoint, so nothing is
        lost.  Attempts are bounded (``MAX_OOM_BACKOFFS`` per fit);
        exhaustion or an un-backoffable chunk re-raises the ORIGINAL
        error with the remedy chained in.  Returns ``(result, chunk)``
        — the chunk that succeeded, sticky for later segments.

        The ``faults.on_segment_dispatch`` injection point fires INSIDE
        the try block, so an injected ``SimulatedOOM`` exercises
        exactly the recovery a real XLA OOM takes.

        Telemetry (ISSUE 11): ONE ``segment`` span wraps the whole
        retry loop; each attempt is a nested ``dispatch`` span stamped
        with its chunk and attempt index — so a replayed segment adds
        attempt spans inside the SAME segment span, never a second
        segment (the no-double-counting contract
        tests/test_obs.py pins).  With a tracer active the segment also
        opens with an ADVISORY memory check
        (``obs.memory.advise_dispatch``, ISSUE 12): predicted tile
        bytes vs device-free logged as a ``mem.plan`` event and the
        ``fit.mem_planned_chunk`` gauge — informational, the chunk is
        never steered by it."""
        import warnings
        import jax
        attempt = 0
        with obs_trace.span("segment", index=segment):
            # Advisory pre-dispatch memory check (ISSUE 12): with a
            # tracer active, log predicted tile footprint vs device-free
            # bytes and record the ``fit.mem_planned_chunk`` gauge.
            # Advisory ONLY — never raises, never changes the chunk;
            # the reactive backoff below stays the enforcement path.
            obs_memory.advise_dispatch(self, chunk, segment=segment)
            while True:
                try:
                    with obs_trace.span("dispatch", tag="fit/segment",
                                        chunk=chunk, attempt=attempt):
                        faults.on_segment_dispatch(segment, chunk)
                        result = dispatch(chunk)
                        # Materialize INSIDE the try: JAX dispatch is
                        # async, so a real device RESOURCE_EXHAUSTED
                        # raised during execution would otherwise
                        # surface later, at the caller's first
                        # np.asarray — outside this recovery path
                        # (review r10).  The outputs are small (tables
                        # + histories), so the sync costs one round
                        # trip the segment boundary pays anyway.
                        jax.block_until_ready(result)
                    return result, chunk
                except Exception as e:       # noqa: BLE001 — reclassified
                    if not is_oom_error(e):
                        raise
                    from kmeans_tpu.parallel.sharding import backoff_chunk
                    smaller = backoff_chunk(chunk)
                    if smaller is None or self.oom_backoffs_ >= \
                            MAX_OOM_BACKOFFS:
                        # Plain RuntimeError (not type(e) — injected
                        # OOMs have a structured constructor), original
                        # chained.
                        raise RuntimeError(
                            f"{e}; chunk backoff exhausted at {chunk} "
                            f"rows after {self.oom_backoffs_} "
                            f"halving(s) — this working set does not "
                            f"fit at the minimum scan chunk; shrink "
                            f"k/D, add devices, or resume the "
                            f"checkpoint on a larger mesh") from e
                    attempt += 1
                    self.oom_backoffs_ += 1
                    self.effective_chunk_ = smaller
                    # Write-through (ISSUE 11): the per-fit audit attr
                    # stays the documented surface; the registry keeps
                    # the process-wide view.
                    obs_metrics.REGISTRY.counter("fit.oom_backoffs").inc()
                    warnings.warn(
                        f"device OOM dispatching segment {segment} at "
                        f"chunk {chunk}; retrying at chunk {smaller} "
                        f"(backoff "
                        f"{self.oom_backoffs_}/{MAX_OOM_BACKOFFS}; the "
                        f"segment replays from the last checkpoint "
                        f"boundary, trajectory unchanged)", UserWarning,
                        stacklevel=3)
                    chunk = smaller

    def _raise_divergence(self, quantity: str, iteration: int,
                          detail: str = ""):
        """Roll the fitted state back to the last-good checkpoint (when
        one is active and still loads) and raise
        :class:`NumericalDivergenceError` naming the iteration and the
        offending quantity.  Without an active checkpoint the error
        still names the quantity/iteration — strictly more information
        than the old post-hoc ``ValueError``."""
        path = getattr(self, "_active_ckpt_path", None)
        # Only a checkpoint THIS fit has a stake in may be restored: one
        # it wrote, or the very state it resumed from.  A stale file an
        # earlier fit left at a reused path stays untouched (review
        # r10) — the error still names the path so the operator can
        # inspect it.
        own = getattr(self, "_ckpt_written_this_fit", False) or (
            path is not None
            and getattr(self, "_resumed_from", None) == os.fspath(path))
        rolled = None
        if path is not None and own:
            try:
                state, _ = ckpt.load_state_with_fallback(path)
            except Exception:
                state = None
            k_attr = self._ckpt_k_attr
            if state is not None and \
                    state.get("model_class", type(self).__name__) \
                    == type(self).__name__ and \
                    int(state.get(k_attr, getattr(self, k_attr))) \
                    == getattr(self, k_attr):
                self._restore_state(state)
                rolled = int(state.get("iterations_run",
                                       state.get("n_iter_", 0)))
        # Name the path only when a rollback was actually eligible: a
        # fit with no stake in the file must not send the operator off
        # to debug "could not be restored" for a checkpoint that was
        # never this fit's to restore (review r10).
        raise NumericalDivergenceError(
            quantity, iteration, rolled_back_to=rolled,
            checkpoint_path=path if own else None, detail=detail)

    def _write_autockpt(self, path, iteration: int) -> None:
        """One rotating atomic checkpoint (multi-host primary-gated,
        barriered per segment) + the deterministic fault-injection
        boundary hook.  Also the shared HEARTBEAT point (ISSUE 11):
        every family's segment boundary passes through here, and the
        boundary state is already host-materialized, so a progress
        record costs zero extra dispatches."""
        ckpt.save_state_primary(path, self._state_dict(),
                                f"kmeans_tpu.autockpt.{iteration}",
                                rotate=True)
        self._ckpt_written_this_fit = True
        obs_note_progress(self, phase="checkpoint",
                                    iteration=int(iteration))
        faults.on_checkpoint(iteration, path)

    def _resolve_resume(self, resume):
        """Normalize the ``resume`` argument; a path loads the
        checkpoint (with ``.prev`` fallback) into this model first."""
        if not isinstance(resume, (str, os.PathLike)):
            self._resumed_from = None
            return bool(resume)
        self._resumed_from = os.fspath(resume)
        # AOT read path (ISSUE 15a): executables shipped next to the
        # checkpoint (``<path>.aot``) join the store's lookup dirs, so
        # a resume — including onto a new mesh on a fresh host — loads
        # instead of compiling whatever programs match this topology.
        # A no-op without an active store.
        from kmeans_tpu.utils import aot as _aot
        _aot.on_resume_path(resume)
        state, used_prev = ckpt.load_state_with_fallback(resume)
        if used_prev:
            import warnings
            warnings.warn(
                f"checkpoint {resume} is unreadable; resuming from the "
                f"last-good rotation {ckpt.prev_path(resume)} (one "
                f"checkpoint interval older, same trajectory)",
                UserWarning, stacklevel=3)
        cls_name = state.get("model_class", type(self).__name__)
        if cls_name != type(self).__name__:
            raise ValueError(
                f"checkpoint {resume} was written by {cls_name}, not "
                f"{type(self).__name__}; load it with {cls_name}.load "
                f"or resume with the matching model class")
        k_attr = self._ckpt_k_attr
        if k_attr in state and int(state[k_attr]) != getattr(self, k_attr):
            raise ValueError(
                f"checkpoint {resume} holds a {k_attr}="
                f"{int(state[k_attr])} model; this model has "
                f"{k_attr}={getattr(self, k_attr)}")
        self._restore_state(state)
        return True
