"""Shared auto-checkpointing plumbing for every model family (ISSUE 4).

One mixin carries the three pieces every fault-tolerant fit needs:

* ``_check_ckpt`` — knob validation (``checkpoint_every``/``_path``
  pairing, n_init=1 — a restart sweep re-initializes, so a partial
  sweep has no well-defined resume point);
* ``_write_autockpt`` — the rotating atomic write
  (``utils.checkpoint.save_state_rotating`` under the multi-host
  primary-gated barrier) followed by the deterministic fault-injection
  boundary hook (``utils.faults.on_checkpoint``) — fired only AFTER the
  checkpoint is durable, so an injected kill always leaves a valid
  resume point;
* ``_resolve_resume`` — ``resume`` may be a checkpoint PATH: load it
  (falling back to the last-good ``.prev`` rotation with a warning on
  corruption), sanity-check the model class / cluster count, restore
  the fitted state, and continue as ``resume=True``.

Host classes provide ``_state_dict()`` / ``_restore_state(state)`` (the
same pair ``save``/``load`` use) and declare ``_ckpt_k_attr`` — the
cluster-count constructor attribute ('k' for the K-Means families,
'n_components' for the mixture) checked against the checkpoint.
"""

from __future__ import annotations

import os

from kmeans_tpu.utils import checkpoint as ckpt
from kmeans_tpu.utils import faults


class AutoCheckpointMixin:

    _ckpt_k_attr = "k"

    def _check_ckpt(self, checkpoint_every, checkpoint_path) -> int:
        """Validate the auto-checkpoint knobs (shared by every family's
        fit/fit_stream)."""
        n = int(checkpoint_every)
        if n < 0 or n != checkpoint_every:
            raise ValueError(f"checkpoint_every must be an int >= 0, got "
                             f"{checkpoint_every!r}")
        if n > 0 and checkpoint_path is None:
            raise ValueError("checkpoint_every > 0 requires "
                             "checkpoint_path")
        if n == 0 and checkpoint_path is not None:
            raise ValueError("checkpoint_path requires "
                             "checkpoint_every >= 1")
        if n > 0 and self.n_init != 1:
            raise ValueError(
                "auto-checkpointing (checkpoint_every > 0) requires "
                "n_init == 1: a restart sweep re-initializes, so a "
                "partially-swept fit has no well-defined resume point")
        return n

    def _write_autockpt(self, path, iteration: int) -> None:
        """One rotating atomic checkpoint (multi-host primary-gated,
        barriered per segment) + the deterministic fault-injection
        boundary hook."""
        ckpt.save_state_primary(path, self._state_dict(),
                                f"kmeans_tpu.autockpt.{iteration}",
                                rotate=True)
        faults.on_checkpoint(iteration, path)

    def _resolve_resume(self, resume):
        """Normalize the ``resume`` argument; a path loads the
        checkpoint (with ``.prev`` fallback) into this model first."""
        if not isinstance(resume, (str, os.PathLike)):
            return bool(resume)
        state, used_prev = ckpt.load_state_with_fallback(resume)
        if used_prev:
            import warnings
            warnings.warn(
                f"checkpoint {resume} is unreadable; resuming from the "
                f"last-good rotation {ckpt.prev_path(resume)} (one "
                f"checkpoint interval older, same trajectory)",
                UserWarning, stacklevel=3)
        cls_name = state.get("model_class", type(self).__name__)
        if cls_name != type(self).__name__:
            raise ValueError(
                f"checkpoint {resume} was written by {cls_name}, not "
                f"{type(self).__name__}; load it with {cls_name}.load "
                f"or resume with the matching model class")
        k_attr = self._ckpt_k_attr
        if k_attr in state and int(state[k_attr]) != getattr(self, k_attr):
            raise ValueError(
                f"checkpoint {resume} holds a {k_attr}="
                f"{int(state[k_attr])} model; this model has "
                f"{k_attr}={getattr(self, k_attr)}")
        self._restore_state(state)
        return True
