"""Spherical K-Means: cosine-similarity clustering on a TPU mesh.

A beyond-reference model family (the reference is Euclidean-only,
kmeans_spark.py:153) aimed at embedding workloads — the GloVe-class configs
in BASELINE.json cluster word vectors, where direction matters and magnitude
is noise.

TPU-first design: for unit vectors, squared Euclidean distance is
``2 - 2*cos`` — a monotone transform of cosine similarity — so maximizing
cosine similarity IS minimizing the Euclidean distance the existing fused
MXU kernel already computes.  The whole model is therefore two projections
around the unchanged SPMD step:

* points are L2-normalized ONCE at caching time (rows with zero norm are
  left at the origin: they have no direction, and are equidistant-by-cosine
  from everything);
* centroids are re-projected onto the unit sphere after every mean update
  (the spherical Lloyd step: mean direction = normalized weighted sum),
  via the ``_postprocess_centroids`` hook.

No new kernel, no new collective, no second code path — the distance
matmul, one-hot scatter-sum, psum, empty-cluster policies, checkpointing,
and mesh sharding are all inherited.

Elastic resume (ISSUE 5): inherited unchanged from :class:`KMeans` —
checkpoints are canonical (k, D) unit-direction tables with the topology
metadata block, so a spherical fit checkpointed on one mesh resumes on
any other (``tests/test_elastic.py`` pins the cross-mesh matrix cell);
the OOM chunk backoff and divergence rollback
(``NumericalDivergenceError``) apply to the projected device loop
exactly as to plain Lloyd.
"""

from __future__ import annotations

import numpy as np

from kmeans_tpu.models.kmeans import KMeans


def _normalize_rows(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=-1, keepdims=True)
    return x / np.maximum(norms, np.finfo(np.float64).tiny)


class SphericalKMeans(KMeans):
    """K-Means on the unit sphere (cosine-similarity clustering).

    Same constructor surface as :class:`KMeans`, INCLUDING ``host_loop``
    (ISSUE 2 satellite — the r5 pin on ``host_loop=True`` is gone): the
    sphere projection now has an exact device twin folded into the
    one-dispatch ``lax.while_loop`` fit's update step
    (``parallel.distributed._project_centroids``, declared via
    ``_device_project``), so ``host_loop=False`` runs the whole fit as
    one dispatch and ``host_loop='auto'`` (the default) may switch to it
    on high-dispatch-latency platforms exactly like the base class —
    trajectory parity host-vs-device is pinned by
    ``tests/test_spherical.py::test_spherical_device_loop_matches_host``.

    Semantics:

    * ``fit``/``predict``/``score`` L2-normalize their inputs, so callers
      may pass raw (un-normalized) vectors.
    * ``centroids`` are unit-norm mean directions.
    * ``sse_history``/``inertia_``/``score`` are sums of ``2 - 2*cos`` —
      the squared chordal distance on normalized data (monotone in total
      cosine similarity).
    * ``transform`` returns chordal distances to each centroid; cosine
      similarity is ``1 - d**2 / 2``.
    """

    _device_project = "sphere"

    def __init__(self, k: int = 3, max_iter: int = 100,
                 tolerance: float = 1e-4, seed: int = 42,
                 compute_sse: bool = False, **kwargs):
        super().__init__(k=k, max_iter=max_iter, tolerance=tolerance,
                         seed=seed, compute_sse=compute_sse, **kwargs)

    def cache(self, X, sample_weight=None):
        """Upload L2-normalized rows (zero rows stay at the origin)."""
        X = _normalize_rows(np.asarray(X, dtype=np.float64))
        ds = super().cache(X.astype(self.dtype),
                           sample_weight=sample_weight)
        ds._unit_rows = True         # marks data as cosine-ready
        return ds

    def _dataset(self, X):
        """Reject pre-built ShardedDatasets that did not go through this
        model's normalizing ``cache`` — raw magnitudes would silently break
        the cosine semantics (centroids sphere-projected, points not)."""
        from kmeans_tpu.parallel.sharding import ShardedDataset
        if isinstance(X, ShardedDataset) and \
                not getattr(X, "_unit_rows", False):
            raise ValueError(
                "SphericalKMeans requires row-normalized data: cache it "
                "with SphericalKMeans.cache(X) (or pass the raw array) "
                "instead of a ShardedDataset built elsewhere")
        return super()._dataset(X)

    def _postprocess_centroids(self, centroids: np.ndarray,
                               prev=None) -> np.ndarray:
        """The spherical Lloyd step: mean direction = normalized mean.

        A zero mean (perfectly cancelling members) has no direction; that
        cluster keeps its previous centroid direction for this iteration
        (an origin centroid would wrongly capture every point more than 60
        degrees from all real centroids, since d^2 to the origin is 1 for
        unit points).  At init (``prev=None``) rows are data points and
        only an all-zero data row can be zero — it is left as-is.
        """
        norms = np.linalg.norm(centroids, axis=1, keepdims=True)
        unit = _normalize_rows(centroids)
        fallback = centroids if prev is None else prev
        return np.where(norms > 0, unit, fallback)

    # Tag: this hook has an EXACT device twin (the 'sphere' branch of
    # parallel.distributed._project_centroids), which is what lets
    # host_loop=False/'auto' run SphericalKMeans in one dispatch; a user
    # subclass overriding _postprocess_centroids loses the tag and is
    # routed back to the host loop (kmeans._resolve_host_loop).
    _postprocess_centroids._device_equivalent = "sphere"

    def _quality_rows(self, X) -> "np.ndarray":
        """Quality-profile geometry (ISSUE 14): rows L2-normalize in
        float64 before distancing, so ``quality_profile(X=...)`` scores
        the same chordal ``2 - 2*cos`` distances serving ``score_rows``
        computes (centroids are unit vectors)."""
        return _normalize_rows(np.asarray(X, np.float64))

    def fitted_state(self) -> dict:
        """Serving handle (ISSUE 6): same table shape/stacking as the
        base class, but requests must be row-normalized before
        assignment — ``normalize_inputs=True`` tells the serving engine
        to run ``_normalize_rows`` on every request's rows (matching
        what ``predict`` does via the normalizing ``cache``), so a
        spherical model can still pack with plain K-Means models of the
        same (k, D, dtype) in one routed dispatch."""
        spec = super().fitted_state()
        spec["normalize_inputs"] = True
        return spec

    def _sweep_metric_rows(self, X) -> np.ndarray:
        """Metric-criterion rows for ``sweep`` (ISSUE 7): the sweep's
        labels are assignments of L2-NORMALIZED rows (this model's
        ``cache`` normalizes), so silhouette/CH/DB must score the same
        unit-sphere geometry — chordal distances on normalized rows,
        monotone in cosine similarity — or the curve would mix cosine
        labels with Euclidean-magnitude scatter."""
        return np.ascontiguousarray(_normalize_rows(
            np.asarray(X, np.float64)).astype(np.float32))

    def transform(self, X, *, block_rows=None) -> np.ndarray:
        """Chordal distances ``sqrt(2 - 2*cos)`` to each centroid, (n, k);
        cosine similarity is ``1 - d**2 / 2``.  Rows are L2-normalized by
        the ``_iter_stream_blocks`` override the base implementation
        streams through (normalizing here too would pay a second
        full-array float64 pass, review r4)."""
        return super().transform(X, block_rows=block_rows)

    # ------------------------------------------------------------ streaming
    # The streaming paths receive raw host blocks that never pass through
    # this model's normalizing ``cache`` — wrap them so magnitudes cannot
    # silently break the cosine semantics (found r4: the inherited
    # fit_stream/predict_stream ran on un-normalized blocks).

    def _normalized_blocks(self, make_blocks):
        def wrapped():
            for item in make_blocks():
                if isinstance(item, tuple):      # (block, weights) pair
                    b, w = item
                    yield (_normalize_rows(
                        np.asarray(b, np.float64)).astype(self.dtype), w)
                else:
                    yield _normalize_rows(
                        np.asarray(item, np.float64)).astype(self.dtype)
        return wrapped

    def fit_stream(self, make_blocks, *, d=None, resume=False,
                   prefetch: int = 2, checkpoint_every: int = 0,
                   checkpoint_path=None, io_retries: int = 0,
                   io_backoff: float = 0.05,
                   on_nonfinite: str = "error") -> "SphericalKMeans":
        # The fault-tolerance knobs wrap OUTSIDE the normalization (base
        # class order), so retry replays re-normalize deterministically
        # and the non-finite scan sees what the fit would consume.
        return super().fit_stream(self._normalized_blocks(make_blocks),
                                  d=d, resume=resume, prefetch=prefetch,
                                  checkpoint_every=checkpoint_every,
                                  checkpoint_path=checkpoint_path,
                                  io_retries=io_retries,
                                  io_backoff=io_backoff,
                                  on_nonfinite=on_nonfinite)

    def _iter_stream_blocks(self, make_blocks, *, with_weights: bool,
                            prefetch: int = 0, stage_extra=None):
        """One choke point for every streaming inference/scoring surface
        (predict/transform/score streams all route through here): wrapping
        per public method instead let ``score_stream`` ship un-normalized
        (advisor r4), and a future base-class stream method would repeat
        that bug.  ``fit_stream`` has its own path and wraps separately.
        With ``prefetch > 0`` the normalization runs in the producer
        thread too (the wrapped generator is driven from there)."""
        return super()._iter_stream_blocks(
            self._normalized_blocks(make_blocks), with_weights=with_weights,
            prefetch=prefetch, stage_extra=stage_extra)
