"""Diagonal-covariance Gaussian mixture — EM on the K-Means machinery.

A beyond-reference model family (the reference framework is K-Means
only, SURVEY.md §1): sklearn-style ``GaussianMixture`` whose E-step runs
as the same chunked, data-sharded, psum-reduced SPMD pass as the K-Means
assignment step (``parallel.gmm_step``), with the two (chunk, k)
log-density matmuls on the MXU.  Host-side M-step in float64 (mirroring
``KMeans``'s host centroid division), sklearn-compatible surface:
``fit`` / ``predict`` / ``predict_proba`` / ``score`` /
``score_samples`` / ``sample`` / ``aic`` / ``bic``, attributes
``weights_`` / ``means_`` / ``covariances_`` / ``precisions_`` /
``converged_`` / ``n_iter_`` / ``lower_bound_``.

Only ``covariance_type='diag'`` is implemented — it is the one diagonal
fit to the TPU formulation (full covariances need per-component k x D x D
solves that leave the matmul-dominant regime); 'spherical' is a special
case users can get by tying ``covariances_`` afterwards.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from kmeans_tpu.parallel.gmm_step import (EStats, make_gmm_predict_fn,
                                          make_gmm_step_fn)
from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)
from kmeans_tpu.utils.validation import check_finite_array

_STEP_CACHE: dict = {}
# Softmax sharpness for the hard-assignment init pass: with inv_var this
# large, the nearest-centroid log-density dominates by >>f32 range, so
# responsibilities are exactly one-hot (sklearn inits from one-hot
# KMeans-label responsibilities too).
_HARD_INV_VAR = 1e6


def _get_fns(mesh: Mesh, chunk: int):
    key = (mesh, chunk, "gmm")
    if key not in _STEP_CACHE:
        _STEP_CACHE[key] = (make_gmm_step_fn(mesh, chunk_size=chunk),
                            make_gmm_predict_fn(mesh, chunk_size=chunk))
    return _STEP_CACHE[key]


class GaussianMixture:
    """sklearn-style diagonal GMM, data-sharded over the TPU mesh.

    Parameters follow ``sklearn.mixture.GaussianMixture`` where they
    overlap (``n_components``, ``tol``, ``reg_covar``, ``max_iter``,
    ``init_params``: 'kmeans' | 'k-means++' | 'random', explicit
    ``weights_init`` / ``means_init`` / ``precisions_init``); ``seed``,
    ``mesh``, ``chunk_size``, ``dtype``, ``verbose`` follow this
    framework's ``KMeans``.  ``lower_bound_`` is the mean per-sample
    log-likelihood, and convergence is its absolute change < ``tol``
    (sklearn semantics).
    """

    def __init__(self, n_components: int = 1, *,
                 covariance_type: str = "diag", tol: float = 1e-3,
                 reg_covar: float = 1e-6, max_iter: int = 100,
                 init_params: str = "kmeans", weights_init=None,
                 means_init=None, precisions_init=None, seed: int = 42,
                 dtype=None, mesh: Optional[Mesh] = None,
                 chunk_size: Optional[int] = None, verbose: bool = False):
        if covariance_type != "diag":
            raise ValueError(
                "only covariance_type='diag' is implemented (see module "
                f"docstring), got {covariance_type!r}")
        if n_components < 1:
            raise ValueError(f"n_components must be >= 1, "
                             f"got {n_components}")
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        if tol < 0 or reg_covar < 0:
            raise ValueError("tol and reg_covar must be >= 0")
        if init_params not in ("kmeans", "k-means++", "kmeans++", "random"):
            raise ValueError(f"unknown init_params {init_params!r}")
        self.n_components = n_components
        self.covariance_type = covariance_type
        self.tol = tol
        self.reg_covar = reg_covar
        self.max_iter = max_iter
        self.init_params = init_params
        self.weights_init = weights_init
        self.means_init = means_init
        self.precisions_init = precisions_init
        self.seed = seed
        self.dtype = np.dtype(jax.dtypes.canonicalize_dtype(
            np.dtype(dtype) if dtype is not None else np.float32))
        self.mesh = mesh
        self.chunk_size = chunk_size
        self.verbose = verbose

        self.weights_: Optional[np.ndarray] = None
        self.means_: Optional[np.ndarray] = None
        self.covariances_: Optional[np.ndarray] = None
        self.converged_: bool = False
        self.n_iter_: int = 0
        self.lower_bound_: float = -np.inf

    # ------------------------------------------------------------- plumbing

    def _resolve_mesh(self) -> Mesh:
        if self.mesh is None:
            self.mesh = make_mesh(model=1)
        return self.mesh

    def _dataset(self, X, sample_weight=None) -> ShardedDataset:
        if isinstance(X, ShardedDataset):
            return X
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        check_finite_array(X, "Data contains NaN or Inf values")
        mesh = self._resolve_mesh()
        data_shards, _ = mesh_shape(mesh)
        chunk = self.chunk_size or choose_chunk_size(
            -(-X.shape[0] // data_shards), self.n_components, X.shape[1])
        return to_device(X, mesh, chunk, self.dtype,
                         sample_weight=sample_weight)

    def _params_dev(self):
        a = 1.0 / np.maximum(self.covariances_, self.reg_covar)
        return (jnp.asarray(self.means_.astype(self.dtype)),
                jnp.asarray(a.astype(self.dtype)),
                jnp.asarray(np.log(self.covariances_).sum(1)
                            .astype(self.dtype)),
                jnp.asarray(np.log(self.weights_).astype(self.dtype)))

    # ----------------------------------------------------------------- init

    def _init_params(self, ds: ShardedDataset, step_fn):
        d = ds.d
        k = self.n_components
        if self.means_init is not None:
            means = np.asarray(self.means_init, np.float64)
            if means.shape != (k, d):
                raise ValueError(f"means_init shape {means.shape} != "
                                 f"({k}, {d})")
        else:
            if self.init_params == "random":
                # sklearn 'random' draws random responsibilities; seeding
                # means at random points is the established analogue.
                from kmeans_tpu.models.init import forgy_init
                means = np.asarray(forgy_init(ds, k, self.seed,
                                              validate=False), np.float64)
            else:
                # Both 'kmeans' and 'k-means++' seed the internal KMeans
                # with D^2 (k-means++) sampling — sklearn's 'kmeans' mode
                # also runs its KMeans with init='k-means++'; 'k-means++'
                # here skips the Lloyd refinement (seeding only).
                from kmeans_tpu.models.kmeans import KMeans
                refine = 20 if self.init_params == "kmeans" else 1
                km = KMeans(k=k, seed=self.seed, init="kmeans++",
                            max_iter=refine, verbose=False,
                            compute_labels=False, mesh=self.mesh,
                            empty_cluster="resample")
                km._eager_labels = False
                km.fit(ds)
                means = np.asarray(km.centroids, np.float64)

        # One HARD-assignment E-step (inv_var >> data scale makes the
        # softmax one-hot) yields the per-component one-hot statistics
        # sklearn also inits from; M-step below turns them into
        # weights/covariances.  Explicit precisions/weights_init override.
        hard = step_fn(ds.points, ds.weights,
                       jnp.asarray(means.astype(self.dtype)),
                       jnp.full((k, d), self.dtype.type(_HARD_INV_VAR)),
                       jnp.zeros((k,), self.dtype),
                       jnp.zeros((k,), self.dtype))
        w_total, (pi, mu, var) = self._m_step(hard)
        self.means_ = mu if self.means_init is None else means
        self.weights_ = (pi if self.weights_init is None
                         else np.asarray(self.weights_init, np.float64))
        if self.precisions_init is not None:
            self.covariances_ = 1.0 / np.asarray(self.precisions_init,
                                                 np.float64)
        else:
            self.covariances_ = var
        self.weights_ = self.weights_ / self.weights_.sum()
        return w_total

    # ------------------------------------------------------------------- EM

    def _m_step(self, st: EStats):
        """float64 host M-step from the psum-reduced E statistics."""
        R = np.asarray(st.resp_sum, np.float64)
        S1 = np.asarray(st.xsum, np.float64)
        S2 = np.asarray(st.x2sum, np.float64)
        w_total = float(R.sum())
        Rc = np.maximum(R, 10 * np.finfo(np.float64).tiny)
        mu = S1 / Rc[:, None]
        var = S2 / Rc[:, None] - mu ** 2 + self.reg_covar
        var = np.maximum(var, self.reg_covar)
        pi = np.maximum(R / max(w_total, 1e-300), 1e-300)
        return w_total, (pi / pi.sum(), mu, var)

    def fit(self, X, sample_weight=None) -> "GaussianMixture":
        ds = self._dataset(X, sample_weight)
        mesh = self._resolve_mesh()
        step_fn, _ = _get_fns(mesh, ds.chunk)
        self._fit_chunk = ds.chunk
        w_total = self._init_params(ds, step_fn)
        if w_total <= 0:
            raise ValueError("total sample weight must be positive")

        self.converged_ = False
        prev = -np.inf
        for it in range(1, self.max_iter + 1):
            t0 = time.perf_counter()
            st: EStats = step_fn(ds.points, ds.weights, *self._params_dev())
            _, (pi, mu, var) = self._m_step(st)
            self.weights_, self.means_, self.covariances_ = pi, mu, var
            self.lower_bound_ = float(st.loglik) / w_total
            self.n_iter_ = it
            if self.verbose:
                print(f"EM iteration {it}: mean log-likelihood = "
                      f"{self.lower_bound_:.6f} "
                      f"[{(time.perf_counter() - t0) * 1e3:.1f} ms]",
                      flush=True)
            if not np.isfinite(self.lower_bound_):
                raise ValueError(
                    f"non-finite log-likelihood at EM iteration {it}")
            if abs(self.lower_bound_ - prev) < self.tol:
                self.converged_ = True
                break
            prev = self.lower_bound_
        return self

    # ------------------------------------------------------------ inference

    def _check_fitted(self):
        if self.means_ is None:
            raise ValueError("Model must be fitted before prediction")

    def _posterior(self, X):
        self._check_fitted()
        ds = self._dataset(X)
        mesh = self._resolve_mesh()
        _, predict_fn = _get_fns(mesh, ds.chunk)
        labels, logr, lse = predict_fn(ds.points, *self._params_dev())
        return (np.asarray(labels)[: ds.n],
                np.asarray(logr)[: ds.n].astype(np.float64),
                np.asarray(lse)[: ds.n].astype(np.float64))

    def predict(self, X) -> np.ndarray:
        return self._posterior(X)[0]

    def predict_proba(self, X) -> np.ndarray:
        return np.exp(self._posterior(X)[1])

    def score_samples(self, X) -> np.ndarray:
        """Per-sample log-likelihood log p(x) under the mixture."""
        return self._posterior(X)[2]

    def score(self, X, y=None) -> float:
        """Mean per-sample log-likelihood (sklearn convention)."""
        return float(np.mean(self.score_samples(X)))

    def sample(self, n_samples: int = 1):
        """Draw (X, component_labels) from the fitted mixture."""
        self._check_fitted()
        rng = np.random.default_rng(self.seed)
        comp = rng.choice(self.n_components, size=n_samples,
                          p=self.weights_ / self.weights_.sum())
        X = (self.means_[comp]
             + rng.standard_normal((n_samples, self.means_.shape[1]))
             * np.sqrt(self.covariances_[comp]))
        return X.astype(self.dtype), comp.astype(np.int32)

    # ----------------------------------------------------- model selection

    @property
    def precisions_(self) -> np.ndarray:
        self._check_fitted()
        return 1.0 / self.covariances_

    def _n_parameters(self) -> int:
        k, d = self.n_components, self.means_.shape[1]
        return (k - 1) + k * d + k * d

    def bic(self, X) -> float:
        n = np.asarray(X).shape[0] if not isinstance(X, ShardedDataset) \
            else X.n
        return (-2.0 * self.score(X) * n
                + self._n_parameters() * math.log(n))

    def aic(self, X) -> float:
        n = np.asarray(X).shape[0] if not isinstance(X, ShardedDataset) \
            else X.n
        return -2.0 * self.score(X) * n + 2.0 * self._n_parameters()

    def get_params(self, deep: bool = True) -> dict:
        return {"n_components": self.n_components,
                "covariance_type": self.covariance_type, "tol": self.tol,
                "reg_covar": self.reg_covar, "max_iter": self.max_iter,
                "init_params": self.init_params,
                "weights_init": self.weights_init,
                "means_init": self.means_init,
                "precisions_init": self.precisions_init,
                "seed": self.seed, "dtype": self.dtype, "mesh": self.mesh,
                "chunk_size": self.chunk_size, "verbose": self.verbose}

    def set_params(self, **params) -> "GaussianMixture":
        valid = self.get_params()
        for name, value in params.items():
            if name not in valid:
                raise ValueError(f"invalid parameter {name!r} for "
                                 f"GaussianMixture")
            setattr(self, name, value)
        return self
