"""Gaussian mixture — EM on the K-Means machinery, all four sklearn
covariance types.

A beyond-reference model family (the reference framework is K-Means
only, SURVEY.md §1): sklearn-style ``GaussianMixture`` whose E-step runs
as the same chunked, data-sharded, psum-reduced SPMD pass as the K-Means
assignment step (``parallel.gmm_step``), with the log-density matmuls on
the MXU.  ``covariance_type`` (r3 VERDICT #5 — diag-only was a porting
wall for sklearn users, whose default is 'full'):

* ``'diag'`` — the fast path: two (chunk, k) matmuls per tile.
* ``'spherical'`` — the diag kernel unchanged with the per-component
  scalar variance broadcast over D; only the M-step differs (average
  the per-dim variances).
* ``'tied'`` — ONE shared precision Cholesky P: transform once per
  chunk (``xt = xc @ P``, a single matmul) and the quadratic form
  collapses to the SAME two-matmul shape as diag.  The M-step uses the
  loop-INVARIANT total scatter (computed once per fit) — no
  per-component second moment is ever accumulated.
* ``'full'`` — per-component precision Cholesky (k, D, D): the density
  transform is one batched einsum (k matmuls on the MXU) and the
  M-step moment is a dense psum-reducible (k, D, D) scatter tensor
  accumulated as batched outer-product matmuls.  Crossover: diag costs
  O(n k D) per pass, full O(n k D^2) — at D=128 full is ~128x the
  E-step FLOPs, so keep 'diag' (this framework's default) unless the
  clusters are genuinely correlated.

Composes with the framework's engines like KMeans does (r2 VERDICT
next-round #3):

* ``model_shards > 1`` row-shards the (k, D) parameter tables over the
  mesh's model axis (component/TP sharding);
* ``host_loop=False`` runs ALL EM iterations in one dispatch under a
  device-side ``lax.while_loop`` — all four covariance types
  (full/tied factor their Cholesky on device per iteration,
  ``gmm_step.make_gmm_fit_full_fn``/``_tied_fn``);
* ``n_init`` runs seeded restarts (host-sequential; the winner is the
  restart with the highest final ``lower_bound_``).

Numerics: every E pass works in a CENTERED frame — the data's global
mean is subtracted chunk-by-chunk in registers and added back to the
means after the M-step.  Responsibilities and log-likelihood are exactly
shift-invariant, but centering keeps the accumulated second moments at
the data's SPREAD scale, so ``S2/R - mu^2`` no longer cancels below f32
precision for data with ``|mean|/std >~ 1e3`` (r2 ADVICE, medium — the
uncentered form silently collapsed covariances to the ``reg_covar``
clamp; sklearn avoids it by accumulating in float64).

``covariances_`` follows sklearn's shape convention per type: (k, D)
diag, (k,) spherical, (D, D) tied, (k, D, D) full.
"""

from __future__ import annotations

import contextlib
import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.parallel.gmm_step import (EStats, EStatsFull,
                                          make_gmm_fit_fn,
                                          make_gmm_fit_full_fn,
                                          make_gmm_fit_tied_fn,
                                          make_gmm_multi_fit_fn,
                                          make_gmm_predict_fn,
                                          make_gmm_predict_full_fn,
                                          make_gmm_predict_tied_fn,
                                          make_gmm_step_fn,
                                          make_gmm_step_full_fn,
                                          make_gmm_step_tied_fn,
                                          make_total_scatter_fn)
from kmeans_tpu.parallel.mesh import MODEL_AXIS, make_mesh, mesh_shape
from kmeans_tpu.parallel.sharding import (ShardedDataset, choose_chunk_size,
                                          to_device)
from kmeans_tpu.models.fault_tolerance import AutoCheckpointMixin
from kmeans_tpu.parallel.multihost import fleet_barrier
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs import note_progress as obs_note_progress
from kmeans_tpu.utils.validation import check_finite_array

from kmeans_tpu.utils.cache import LRUCache

# LRU-bounded like models.kmeans._STEP_CACHE (r3 VERDICT weak #7).
_STEP_CACHE = LRUCache(64, name="gmm._STEP_CACHE")
# Softmax sharpness for the hard-assignment init pass: with inv_var this
# large, the nearest-centroid log-density dominates by >>f32 range, so
# responsibilities are exactly one-hot (sklearn inits from one-hot
# KMeans-label responsibilities too).
_HARD_INV_VAR = 1e6
# Per-tile element budget for EM chunking (measured 2x vs the K-Means
# 2^25 budget at k=256-class shapes, docs/PERFORMANCE.md).  Exported so
# data-loader users can request EM-sized chunks:
# ``data.io.from_npy(..., budget_elems=EM_CHUNK_BUDGET)``.
EM_CHUNK_BUDGET = 1 << 23
# Row cap for clamped FOREIGN datasets (``_eff_chunk``): the EM chunk
# sweep (experiments/exp_gmm_estep_retry.py, re-swept at every
# precision) measured 32768 rows optimal with 65536+ collapsing at the
# probe shape, so a dataset whose baked-in chunk survived the element
# budget on k alone (small-k fits of a large single-chunk shard) is
# additionally bounded near that plateau rather than scanning wherever
# the budget allows (ADVICE r5 low).  ``_dataset``'s own auto choice is
# budget-driven and unchanged.  The r8 pipelined schedule carries one
# extra in-flight (chunk, k) logp tile + a centered chunk copy in the
# scan carry, which shifts the fusion-boundary economics this plateau
# priced — the re-sweep under pipeline=1 is part of the pinned hardware
# run (experiments/exp_gmm_pipelined_estep.py; the CPU smoke measured
# the plateau flat here, so 32768 stands until hardware says otherwise).
EM_MAX_CHUNK = 32768

# Weighted-mean pass for the centering shift (GSPMD: XLA inserts the
# cross-shard collectives for the sharded matvec itself).  The zero-
# weight guard is TINY, not 1.0 — clamping at 1.0 would scale the shift
# down whenever total weight < 1 and re-open the cancellation regime the
# shift exists to close.
_mean_jit = jax.jit(lambda p, w: (w @ p.astype(jnp.float32))
                    / jnp.maximum(jnp.sum(w.astype(jnp.float32)),
                                  jnp.finfo(jnp.float32).tiny))


_STEP_BUILDERS = {
    # 'spherical' broadcasts its scalar variances over D and reuses the
    # diag kernels unchanged.
    "diag": (make_gmm_step_fn, make_gmm_predict_fn),
    "spherical": (make_gmm_step_fn, make_gmm_predict_fn),
    "tied": (make_gmm_step_tied_fn, make_gmm_predict_tied_fn),
    "full": (make_gmm_step_full_fn, make_gmm_predict_full_fn),
}


def _get_fns(mesh: Mesh, chunk: int, cov_type: str = "diag",
             pipeline: int = 1):
    step_b, pred_b = _STEP_BUILDERS[cov_type]
    return _STEP_CACHE.get_or_create(
        (mesh, chunk, "gmm", step_b, pred_b, pipeline),
        lambda: (step_b(mesh, chunk_size=chunk, pipeline=pipeline),
                 pred_b(mesh, chunk_size=chunk)))


class GaussianMixture(AutoCheckpointMixin):
    """sklearn-style diagonal GMM, data-sharded over the TPU mesh.

    Parameters follow ``sklearn.mixture.GaussianMixture`` where they
    overlap (``n_components``, ``tol``, ``reg_covar``, ``max_iter``,
    ``n_init``, ``init_params``: 'kmeans' | 'k-means++' | 'random',
    explicit ``weights_init`` / ``means_init`` / ``precisions_init``);
    ``seed``, ``mesh``, ``model_shards``, ``chunk_size``, ``dtype``,
    ``host_loop``, ``verbose`` follow this framework's ``KMeans``.
    ``lower_bound_`` is the mean per-sample log-likelihood, and
    convergence is its absolute change < ``tol`` (sklearn semantics).

    ``host_loop=False`` trades per-iteration host logging for a single
    dispatch (the M-step then divides in the accumulation dtype on
    device instead of the host's float64 — same documented divergence as
    ``KMeans(host_loop=False)``).

    ``pipeline`` ('auto' | 0 | 1) selects the E-step chunk schedule:
    the software-pipelined two-stage scan that overlaps one chunk's
    softmax (VPU) with the next chunk's log-density matmuls (MXU), or
    the serial four-phase body (``pipeline=0`` — the bit-exact parity
    oracle).  'auto' (default) resolves per platform by measurement:
    pipelined on accelerators, serial on CPU (where the carried logp
    tile measured a 0.80x regression with nothing to overlap —
    ``_resolve_pipeline``).  ``estep_path_`` records which schedule a
    fit actually ran ('pipelined' | 'serial').

    Chunking note: raw-array inputs are chunked with the EM-specific
    ``EM_CHUNK_BUDGET`` (2^23 elements; docs/PERFORMANCE.md — the
    K-Means budget costs ~2x per EM iteration at k=256-class shapes).
    A pre-built ``ShardedDataset`` keeps ITS chunk (its padding
    committed to it); when loading data yourself for a mixture fit,
    pass the loader ``budget_elems=EM_CHUNK_BUDGET``
    (``data.io.from_npy``/``from_raw`` forward it).
    """

    _PARAM_NAMES = ("n_components", "covariance_type", "tol", "reg_covar",
                    "max_iter", "n_init", "init_params", "weights_init",
                    "means_init", "precisions_init", "seed", "dtype",
                    "mesh", "model_shards", "chunk_size", "host_loop",
                    "pipeline", "bucket", "overlap", "ingest", "verbose")

    _ckpt_k_attr = "n_components"    # AutoCheckpointMixin resume check

    def __init__(self, n_components: int = 1, *,
                 covariance_type: str = "diag", tol: float = 1e-3,
                 reg_covar: float = 1e-6, max_iter: int = 100,
                 n_init: int = 1, init_params: str = "kmeans",
                 weights_init=None, means_init=None, precisions_init=None,
                 seed: int = 42, dtype=None, mesh: Optional[Mesh] = None,
                 model_shards: int = 1, chunk_size: Optional[int] = None,
                 host_loop: bool = True, pipeline="auto",
                 bucket=0, overlap="auto", ingest: str = "auto",
                 verbose: bool = False):
        if covariance_type not in ("diag", "spherical", "tied", "full"):
            raise ValueError(
                "covariance_type must be one of 'diag', 'spherical', "
                f"'tied', 'full'; got {covariance_type!r}")
        if n_components < 1:
            raise ValueError(f"n_components must be >= 1, "
                             f"got {n_components}")
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        if int(n_init) < 1:
            raise ValueError(f"n_init must be >= 1, got {n_init}")
        if tol < 0 or reg_covar < 0:
            raise ValueError("tol and reg_covar must be >= 0")
        if init_params not in ("kmeans", "k-means++", "kmeans++", "random"):
            raise ValueError(f"unknown init_params {init_params!r}")
        self.n_components = n_components
        self.covariance_type = covariance_type
        self.tol = tol
        self.reg_covar = reg_covar
        self.max_iter = max_iter
        self.n_init = int(n_init)
        self.init_params = init_params
        self.weights_init = weights_init
        self.means_init = means_init
        self.precisions_init = precisions_init
        self.seed = seed
        self.dtype = np.dtype(jax.dtypes.canonicalize_dtype(
            np.dtype(dtype) if dtype is not None else np.float32))
        self.mesh = mesh
        self.model_shards = model_shards
        self.chunk_size = chunk_size
        if isinstance(host_loop, str):
            # KMeans' host_loop='auto' is not implemented for the EM
            # family — reject rather than silently treating the string
            # as truthy-True (review r5).
            raise ValueError("GaussianMixture host_loop must be True or "
                             f"False ('auto' is KMeans-only), got "
                             f"{host_loop!r}")
        self.host_loop = bool(host_loop)
        # E-step chunk schedule (ISSUE 3): 'auto' resolves to the
        # software-pipelined two-stage scan (stage A: next chunk's
        # log-density matmuls; stage B: previous chunk's softmax +
        # moments — parallel.gmm_step._chunked_epass); 0 forces the
        # serial four-phase body, the bit-exact parity oracle (the
        # prefetch=0 discipline of r6).
        if pipeline not in ("auto", 0, 1, True, False):
            raise ValueError(f"pipeline must be 'auto', 0, or 1; got "
                             f"{pipeline!r}")
        self.pipeline = pipeline if pipeline == "auto" else int(pipeline)
        # Fit-shape bucket (ISSUE 15b; the KMeans knob grammar): 0 is
        # the exact-shape bit-parity oracle, 'auto' pads the staged
        # shard to the committed ladder boundary so nearby dataset
        # sizes share one compiled EM program.  Grammar/policy shared
        # with KMeans via parallel.sharding (one definition).
        from kmeans_tpu.parallel.sharding import check_bucket
        self.bucket = check_bucket(bucket)
        # Compile/ingest overlap (ISSUE 18; the KMeans 15c grammar):
        # with 1, a fit on a host array stages the upload through the
        # prefetch producer thread while THIS thread resolves (and AOT-
        # warms) the EM step programs — bit-exact parity with 0, only
        # WHERE the prelude runs moves.  'auto': 0 on CPU, 1 on
        # accelerators (the KMeans resolution, one policy).
        if overlap not in ("auto", 0, 1, True, False):
            raise ValueError(f"overlap must be 'auto', 0, or 1; got "
                             f"{overlap!r}")
        self.overlap = overlap if overlap == "auto" else int(overlap)
        # Ingest placement path (ISSUE 18): grammar shared with KMeans
        # via parallel.sharding; 'mono' is the bit-parity oracle.
        from kmeans_tpu.parallel.sharding import check_ingest
        self.ingest = check_ingest(ingest)
        self.verbose = verbose

        # Which E-step schedule the last fit IN THIS PROCESS ran
        # ('pipelined' | 'serial'); None pre-fit and on loaded models
        # (the schedule is a per-run resolution, not fitted state).
        self.estep_path_: Optional[str] = None
        self.weights_: Optional[np.ndarray] = None
        self.means_: Optional[np.ndarray] = None
        self.covariances_: Optional[np.ndarray] = None
        self.converged_: bool = False
        self.n_iter_: int = 0
        self.lower_bound_: float = -np.inf
        # Centering shift of the last fit's dataset frame, the winning
        # restart index, and the per-restart final lower bounds —
        # declared here (the counter-reset lint discipline) so a read
        # before the first fit is a defined None/0, never an
        # AttributeError or a stale survivor from an earlier fit.
        self.shift_: Optional[np.ndarray] = None
        self.best_restart_: int = 0
        self.restart_lower_bounds_: Optional[np.ndarray] = None
        # Serving-quality reference profile restored from a checkpoint
        # (ISSUE 14); ``quality_profile()`` prefers fresh fitted attrs
        # (weights_/lower_bound_) when they exist.
        self._quality_profile: Optional[dict] = None
        # Fault-tolerance observability (ISSUE 4), mirroring KMeans'.
        self.io_retries_used_: int = 0
        self.blocks_skipped_: int = 0
        self.checkpoint_segments_: Optional[int] = None
        # Heartbeat rows_per_sec input (ISSUE 13), mirroring KMeans'.
        self._progress_rows: Optional[int] = None
        # Elastic recovery observability (ISSUE 5): OOM chunk-backoff
        # count / the device loop's effective chunk (None when no
        # device loop ran; equals the committed chunk on healthy fits —
        # `oom_backoffs_ > 0` is the backoff signal), Cholesky
        # jitter-ladder retries (full/tied host path), and the active
        # checkpoint path the divergence rollback restores from.
        self.oom_backoffs_: int = 0
        self.effective_chunk_: Optional[int] = None
        self.cov_jitter_retries_: int = 0
        self._active_ckpt_path = None
        # Warm-serving parameter-table cache (ISSUE 6): ((weights_,
        # means_, covariances_, mesh) identity token, device tables) —
        # see ``_params_dev``.
        self._params_cache = None
        # Raw accumulation-dtype device-loop tables (means_c/cov/log_w +
        # the carried convergence baseline) captured at the last segment
        # boundary or device-loop finish: the device loop works in the
        # CENTERED frame, and round-tripping through the float64
        # shift-added ``means_`` is not bit-exact ((a + s) - s != a), so
        # bit-exact device-loop resume restores these instead.  None for
        # host-loop fits (whose float64 attrs ARE the exact carry).
        self._dev_tables: Optional[dict] = None

    # ------------------------------------------------------------- plumbing

    def _resolve_pipeline(self) -> int:
        """Resolve the ``pipeline`` knob to the schedule that runs.

        'auto' is platform-aware, per measurement: the two schedules
        are bit-exact parity partners (pinned,
        tests/test_gmm_pipeline.py), so the choice is purely a cost
        call.  On CPU the skewed schedule's carried logp tile is pure
        extra memory traffic — no separate VPU/MXU to overlap — and the
        r8 CPU proxy measured it 0.80x (every interleaved rep slower;
        BASELINE.md) -> 'auto' keeps the serial body there.  On
        accelerators 'auto' -> 1, the schedule built for the MXU-idle
        softmax stall; the hardware before/after (>40% MFU target vs
        the 33% serial baseline at 2M x 128 k=256) is the pinned
        ``gmm-estep-pipeline`` row in BASELINE.json, whose committed
        decision rule flips accelerator-'auto' back to 0 if the overlap
        loses on hardware too.  Every fit records what actually ran in
        ``estep_path_``."""
        if self.pipeline == "auto":
            import jax
            return 0 if jax.default_backend() == "cpu" else 1
        return int(self.pipeline)

    def _note_estep_path(self) -> int:
        """Set the ``estep_path_`` observability attr; returns the
        resolved pipeline flag."""
        p = self._resolve_pipeline()
        self.estep_path_ = "pipelined" if p else "serial"
        return p

    def _resolve_mesh(self) -> Mesh:
        if self.mesh is None:
            self.mesh = make_mesh(model=self.model_shards)
        return self.mesh

    def _dataset(self, X, sample_weight=None) -> ShardedDataset:
        if isinstance(X, ShardedDataset):
            return X
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        check_finite_array(X, "Data contains NaN or Inf values")
        mesh = self._resolve_mesh()
        data_shards, _ = mesh_shape(mesh)
        # The EM pass wants SMALLER (chunk, k) tiles than the K-Means
        # pass: its tile feeds exp + 4 matmuls, and past ~EM_CHUNK_BUDGET
        # elements XLA materializes the logp tile in HBM between
        # fusions.  Measured (v5e, 2M x 128, k=256): chunk 131072 (the
        # K-Means budget) runs 28.6 ms/iter vs 14.2 at 32768 — 2x from
        # chunk sizing alone (3% spreads on both).  Small-k shapes
        # measured too noisy to justify changing their row cap, so only
        # the element budget shrinks (2^25 -> 2^23).
        # 'full' materializes a (chunk, k, D) transform tile per fusion
        # (the batched prec-Cholesky einsum), so its row budget divides
        # by k*D, not k — without this a D=128 full fit would stage a
        # 128x larger intermediate than the diag tile the budget was
        # measured for.
        eff_k = (self.n_components * X.shape[1]
                 if self.covariance_type == "full" else self.n_components)
        # Shape bucket (ISSUE 15b): the chunk derives from the BUCKETED
        # row count and the shard pads up to it, so same-bucket fits
        # share one compiled EM program; bucket=0 (default) is the
        # exact-shape parity oracle.
        n_eff = self._bucket_target(X.shape[0])
        chunk = self.chunk_size or choose_chunk_size(
            -(-n_eff // data_shards), eff_k, X.shape[1],
            budget_elems=EM_CHUNK_BUDGET)
        return to_device(X, mesh, chunk, self.dtype,
                         sample_weight=sample_weight,
                         explicit=self.chunk_size is not None,
                         min_rows=n_eff, ingest=self.ingest)

    def _bucket_target(self, n: int) -> int:
        """Padded-row target of the fit-shape bucket — the one
        committed policy in ``parallel.sharding.bucket_target``."""
        from kmeans_tpu.parallel.sharding import bucket_target
        return bucket_target(self.bucket, n)

    @property
    def _k_pad(self) -> int:
        _, m = mesh_shape(self._resolve_mesh())
        return -(-self.n_components // m) * m

    def _eff_chunk(self, ds) -> int:
        """The dataset chunk, clamped for THIS model's tile footprint
        (ShardedDataset.effective_chunk): a foreign dataset chunked with
        a K-Means-sized ``k_hint`` must not materialize an oversized
        (chunk, k[, D]) EM tile.  Same 'full'-covariance k*D scaling AND
        the same EM_CHUNK_BUDGET as ``_dataset``'s own chunk choice —
        the EM pass measured SMALLER tiles 2x faster (chunk-sizing note
        in ``_dataset``), so the K-Means single-chunk budget must not
        leak in through foreign datasets (r5 review) — and additionally
        bounded by the measured EM row plateau (``EM_MAX_CHUNK``), so a
        small-k clamp that survives the element budget still lands near
        the measured optimum instead of e.g. 50,000 rows."""
        eff_k = (self.n_components * ds.d
                 if self.covariance_type == "full" else self.n_components)
        return ds.effective_chunk(eff_k, EM_CHUNK_BUDGET,
                                  max_chunk=EM_MAX_CHUNK)

    def _resolve_overlap(self) -> int:
        """Resolve the ``overlap`` knob (ISSUE 18; the KMeans 15c
        policy): serial on CPU — both TTFI terms are small there —
        overlapped on accelerators, where the staged transfer is the
        dominant term the compile should hide behind."""
        if self.overlap == "auto":
            return 0 if jax.default_backend() == "cpu" else 1
        return int(self.overlap)

    def _staged_dataset(self, X, sample_weight=None) -> ShardedDataset:
        """The EM fit's dataset prelude (ISSUE 18b): with ``overlap``
        resolved on and a host-array input, the upload runs in the
        prefetch producer thread (``data.prefetch``; its
        'place'/'stage' spans land on the producer tid) while THIS
        thread resolves — and, with an AOT store active,
        loads-or-compiles — the E-step program for the exact padded
        shapes the fit will dispatch (the r19 ``utils.aot`` overlap
        entry point, now on the EM prelude too).  Bit-exact parity
        with the serial path: only WHERE the prelude runs moves."""
        if not self._resolve_overlap() or isinstance(X, ShardedDataset) \
                or jax.process_count() != 1:
            return self._dataset(X, sample_weight)
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            return self._dataset(X, sample_weight)
        from kmeans_tpu.data.prefetch import close_source, prefetch_iter
        it = prefetch_iter([X], 1,
                           stage=lambda B: self._dataset(B, sample_weight))
        try:
            self._warm_em(*X.shape)
            ds = next(it)
        finally:
            close_source(it)
        return ds

    def _warm_em(self, n: int, d: int) -> None:
        """Resolve (and AOT-warm) the E-step program for the (n, d) fit
        about to run — the consumer half of the overlapped prelude.
        The chunk derivation mirrors ``_dataset`` + ``_eff_chunk``
        exactly (the shapes are known before any data moves), so the
        later ``_get_fns`` at the normal fit call site is a pure cache
        hit.  Warming builds sharding-carrying ``ShapeDtypeStruct``s
        for the diag/spherical table layout; tied/full skip the warm
        (their tables are host-factorized per M-step) but still get
        the program resolution overlapped with the ingest."""
        mesh = self._resolve_mesh()
        data_shards, _ = mesh_shape(mesh)
        eff_k = (self.n_components * d
                 if self.covariance_type == "full" else self.n_components)
        n_eff = self._bucket_target(n)
        chunk = self.chunk_size or choose_chunk_size(
            -(-n_eff // data_shards), eff_k, d,
            budget_elems=EM_CHUNK_BUDGET)
        if not self.chunk_size:
            from kmeans_tpu.parallel.sharding import clamp_chunk_for_k
            chunk = clamp_chunk_for_k(chunk, eff_k, EM_CHUNK_BUDGET,
                                      max_chunk=EM_MAX_CHUNK)
        step_fn, _ = _get_fns(mesh, chunk, self.covariance_type,
                              self._resolve_pipeline())
        if not hasattr(step_fn, "warm") \
                or self.covariance_type not in ("diag", "spherical"):
            return
        from jax.sharding import SingleDeviceSharding
        from kmeans_tpu.parallel.mesh import DATA_AXIS
        mult = data_shards * chunk
        n_pad = -(-max(n_eff, n) // mult) * mult
        k_pad = self._k_pad
        sds = jax.ShapeDtypeStruct
        row = NamedSharding(mesh, P(MODEL_AXIS, None))
        vec = NamedSharding(mesh, P(MODEL_AXIS))
        step_fn.warm(
            sds((n_pad, d), self.dtype,
                sharding=NamedSharding(mesh, P(DATA_AXIS, None))),
            sds((n_pad,), self.dtype,
                sharding=NamedSharding(mesh, P(DATA_AXIS))),
            sds((d,), self.dtype,
                sharding=SingleDeviceSharding(jax.devices()[0])),
            sds((k_pad, d), self.dtype, sharding=row),
            sds((k_pad, d), self.dtype, sharding=row),
            sds((k_pad,), self.dtype, sharding=vec),
            sds((k_pad,), self.dtype, sharding=vec))

    def _shift(self) -> np.ndarray:
        """The centering shift (data's global mean), zeros pre-fit."""
        s = getattr(self, "shift_", None)
        if s is None:
            return np.zeros(self.means_.shape[1], np.float64)
        return s

    def _pad_tables(self, means_c, var, log_w):
        """Pad the parameter tables to the model-axis multiple: padding
        components carry ``log_w = -inf`` so they never receive
        responsibility."""
        k, k_pad = self.n_components, self._k_pad
        d = means_c.shape[1]
        mc = np.zeros((k_pad, d), self.dtype)
        mc[:k] = means_c
        vv = np.ones((k_pad, d), self.dtype)
        vv[:k] = var
        lw = np.full((k_pad,), -np.inf, self.dtype)
        lw[:k] = log_w
        return mc, vv, lw

    def _put_tables(self, mesh, means_c, var, log_w):
        """Pad + place the parameter tables row-sharded on the model axis."""
        mc, vv, lw = self._pad_tables(means_c, var, log_w)
        row = NamedSharding(mesh, P(MODEL_AXIS, None))
        vec = NamedSharding(mesh, P(MODEL_AXIS))
        return (jax.device_put(mc, row), jax.device_put(vv, row),
                jax.device_put(lw, vec))

    def _diag_view(self) -> np.ndarray:
        """The (k, D) diagonal-variance view of ``covariances_`` for the
        types the diag kernel serves ('diag' identity, 'spherical'
        broadcast)."""
        if self.covariance_type == "spherical":
            return np.broadcast_to(self.covariances_[:, None],
                                   (self.n_components,
                                    self.means_.shape[1]))
        return self.covariances_

    @staticmethod
    def _prec_chol(cov: np.ndarray):
        """Precision Cholesky (sklearn parameterization) of one or a
        batch of covariance matrices: Sigma = L L^T -> P = L^-T, so
        Sigma^-1 = P P^T and ``log_det_half = sum log diag(P)``.  Raises
        sklearn's ill-defined-covariance error on a non-PD matrix."""
        try:
            L = np.linalg.cholesky(cov)
        except np.linalg.LinAlgError:
            raise ValueError(
                "Fitting the mixture model failed because some "
                "components have ill-defined empirical covariance (for "
                "instance caused by singleton or collapsed samples). "
                "Try to decrease the number of components, or increase "
                "reg_covar.") from None
        eye = np.broadcast_to(np.eye(cov.shape[-1]), cov.shape)
        p_chol = np.swapaxes(np.linalg.solve(L, eye), -1, -2)   # L^-T
        log_det_half = -np.sum(
            np.log(np.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
        return p_chol, log_det_half

    def _prec_chol_guarded(self, cov: np.ndarray):
        """The FIT-path precision Cholesky (ISSUE 5 satellite): on a
        non-PD batch, identify the offending components and retry their
        decomposition with an escalating diagonal jitter ladder
        (``reg_covar * 10^j``, j = 1..3), recording the retries in
        ``cov_jitter_retries_`` — a borderline component one ulp past
        PD (f32 accumulation, near-singleton clusters) continues
        instead of killing an hours-long fit.  The ladder exhausting
        (or ``reg_covar == 0``: nothing to escalate) raises the
        actionable ill-defined-covariance error NAMING the component
        index rather than propagating NaNs.  Healthy batches take the
        plain ``_prec_chol`` path untouched — zero cost, identical
        arithmetic (jitter never mixes into PD components)."""
        try:
            return self._prec_chol(cov)
        except ValueError:
            pass
        single = cov.ndim == 2          # tied: one shared (D, D)
        batch = cov[None] if single else cov
        batch = np.array(batch, dtype=np.float64, copy=True)
        d = batch.shape[-1]
        bad = []
        for idx in range(batch.shape[0]):
            for j in range(4):          # j=0 is the unjittered retry
                jitter = self.reg_covar * (10.0 ** j) if j else 0.0
                try:
                    np.linalg.cholesky(batch[idx]
                                       + jitter * np.eye(d))
                except np.linalg.LinAlgError:
                    continue
                if j:
                    self.cov_jitter_retries_ += 1
                    batch[idx] += jitter * np.eye(d)
                break
            else:
                bad.append(idx)
        if bad:
            names = ("the shared tied covariance" if single else
                     f"component(s) {bad}")
            raise ValueError(
                f"Fitting the mixture model failed because some "
                f"components have ill-defined empirical covariance "
                f"({names} stayed non-PD through the jitter ladder "
                f"reg_covar * 10^j, j <= 3, reg_covar="
                f"{self.reg_covar!r}). Try to decrease the number of "
                f"components, or increase reg_covar.") from None
        import warnings
        warnings.warn(
            f"non-PD covariance rescued by the jitter ladder "
            f"(cov_jitter_retries_={self.cov_jitter_retries_}); "
            f"consider a larger reg_covar", UserWarning, stacklevel=3)
        return self._prec_chol(batch[0] if single else batch)

    def _params_dev(self, mesh, guard_cholesky: bool = False):
        """Device-placed E-step parameter tables, per covariance type.

        INFERENCE calls (``guard_cholesky=False``) are cached on the
        instance keyed by the fitted arrays' IDENTITY and the mesh
        (ISSUE 6 satellite): repeated ``predict``/``predict_proba``/
        ``score_samples`` calls — and every serving-engine dispatch —
        reuse one host-side factorization + device placement instead of
        re-deriving the tables per call.  Fit paths re-assign
        ``means_``/``covariances_``/``weights_`` with fresh arrays
        every M-step, so the identity check invalidates naturally; the
        ``guard_cholesky=True`` fit path never caches (its jitter
        ladder must see the current covariances).

        diag/spherical: (shift, means_c, inv_var, log_det, log_w) — the
        precision AND the log-determinant both come from the SAME
        clamped covariance (r2 ADVICE), floored at the COMPUTE dtype's
        tiny (review r4: a 1e-300 float64 floor flushes to 0 in f32).
        tied: (shift, means_t = mu_c @ P, P (D,D), log_det_half, log_w).
        full: (shift, means_c, P (k,D,D), log_det_half (k,), log_w).

        ``guard_cholesky`` (FIT paths only): route full/tied precision
        factorization through the jitter ladder
        (``_prec_chol_guarded``) so a mid-fit borderline non-PD
        component is rescued.  Inference (predict/score) keeps the
        strict raise — a fitted model whose covariances cannot factor
        must fail loudly, not silently score against jittered densities
        (review r10), and ``cov_jitter_retries_`` stays a pure fit-time
        audit counter."""
        if not guard_cholesky:
            token = (self.weights_, self.means_, self.covariances_, mesh)
            cache = getattr(self, "_params_cache", None)
            if cache is not None and all(a is b for a, b in
                                         zip(cache[0], token)):
                return cache[1]
            params = self._params_dev_build(mesh, guard_cholesky=False)
            self._params_cache = (token, params)
            return params
        return self._params_dev_build(mesh, guard_cholesky=True)

    def _params_dev_build(self, mesh, guard_cholesky: bool = False):
        prec_chol = self._prec_chol_guarded if guard_cholesky \
            else self._prec_chol
        shift = self._shift()
        log_w = np.log(np.maximum(self.weights_, 1e-300))
        ct = self.covariance_type
        if ct in ("diag", "spherical"):
            cv = np.maximum(
                self._diag_view(),
                max(self.reg_covar, float(np.finfo(self.dtype).tiny)))
            means_c, var, log_w_d = self._put_tables(
                mesh, (self.means_ - shift).astype(self.dtype),
                cv.astype(self.dtype), log_w.astype(self.dtype))
            inv_var = 1.0 / var
            log_det = jnp.sum(jnp.log(var), axis=1)
            return (jnp.asarray(shift.astype(self.dtype)), means_c,
                    inv_var, log_det, log_w_d)
        row = NamedSharding(mesh, P(MODEL_AXIS, None))
        vec = NamedSharding(mesh, P(MODEL_AXIS))
        k, k_pad, d = self.n_components, self._k_pad, self.means_.shape[1]
        lw = np.full((k_pad,), -np.inf, self.dtype)
        lw[:k] = log_w
        if ct == "tied":
            p_chol, ldh = prec_chol(
                np.asarray(self.covariances_, np.float64))
            mt = np.zeros((k_pad, d), self.dtype)
            mt[:k] = ((self.means_ - shift) @ p_chol).astype(self.dtype)
            return (jnp.asarray(shift.astype(self.dtype)),
                    jax.device_put(mt, row),
                    jnp.asarray(p_chol.astype(self.dtype)),
                    jnp.asarray(np.asarray(ldh, self.dtype)),
                    jax.device_put(lw, vec))
        # full
        p_chol, ldh = prec_chol(
            np.asarray(self.covariances_, np.float64))
        mc = np.zeros((k_pad, d), self.dtype)
        mc[:k] = (self.means_ - shift).astype(self.dtype)
        pc = np.zeros((k_pad, d, d), self.dtype)
        pc[:k] = p_chol.astype(self.dtype)
        pc[k:] = np.eye(d, dtype=self.dtype)   # benign padding precision
        ldh_pad = np.zeros((k_pad,), self.dtype)
        ldh_pad[:k] = ldh.astype(self.dtype)
        return (jnp.asarray(shift.astype(self.dtype)),
                jax.device_put(mc, row),
                jax.device_put(pc, NamedSharding(
                    mesh, P(MODEL_AXIS, None, None))),
                jax.device_put(ldh_pad, vec), jax.device_put(lw, vec))

    def _trim(self, st):
        k = self.n_components
        if isinstance(st, EStatsFull):
            return EStatsFull(np.asarray(st.resp_sum)[:k],
                              np.asarray(st.xsum)[:k],
                              np.asarray(st.scatter)[:k], st.loglik)
        return EStats(np.asarray(st.resp_sum)[:k], np.asarray(st.xsum)[:k],
                      np.asarray(st.x2sum)[:k], st.loglik)

    # ----------------------------------------------------------------- init

    def _hard_tables(self, mesh, means, shift):
        """Device parameter tables for the HARD-assignment init E-step
        (precision >> data scale -> one-hot responsibilities), shaped
        for this covariance type's step function.  Returns the step
        arguments after (points, weights)."""
        k, d = means.shape[-2], means.shape[-1]
        ct = self.covariance_type
        k_pad = self._k_pad
        sqh = float(np.sqrt(_HARD_INV_VAR))
        mc_pad = np.zeros((k_pad, d), self.dtype)
        mc_pad[:k] = (means - shift).astype(self.dtype)
        lw_pad = np.full((k_pad,), -np.inf, self.dtype)
        lw_pad[:k] = 0.0
        row = NamedSharding(mesh, P(MODEL_AXIS, None))
        vec = NamedSharding(mesh, P(MODEL_AXIS))
        shift_d = jnp.asarray(shift.astype(self.dtype))
        if ct in ("diag", "spherical"):
            return (shift_d, jax.device_put(mc_pad, row),
                    jax.device_put(np.full((k_pad, d), _HARD_INV_VAR,
                                           self.dtype), row),
                    jax.device_put(np.zeros((k_pad,), self.dtype), vec),
                    jax.device_put(lw_pad, vec))
        if ct == "tied":
            # Hard precision Cholesky sqrt(h) * I: means transform to
            # mc * sqrt(h).
            return (shift_d,
                    jax.device_put((mc_pad * sqh).astype(self.dtype),
                                   row),
                    jnp.eye(d, dtype=self.dtype) * sqh,
                    jnp.zeros((), self.dtype),
                    jax.device_put(lw_pad, vec))
        pc = np.broadcast_to(np.eye(d, dtype=self.dtype) * sqh,
                             (k_pad, d, d)).copy()
        return (shift_d, jax.device_put(mc_pad, row),
                jax.device_put(pc, NamedSharding(
                    mesh, P(MODEL_AXIS, None, None))),
                jax.device_put(np.zeros((k_pad,), self.dtype), vec),
                jax.device_put(lw_pad, vec))

    def _restart_seeds(self) -> list:
        """Restart 0 uses ``seed`` exactly; an explicit means_init makes
        every restart identical, so it collapses to one (sklearn too)."""
        if self.means_init is not None:
            return [self.seed]
        extra = np.random.SeedSequence(self.seed).generate_state(
            self.n_init - 1) if self.n_init > 1 else []
        return [self.seed] + [int(s) for s in extra]

    def _init_params(self, ds: ShardedDataset, step_fn, seed: int):
        # 'seed' span (ISSUE 11): the mixture's whole parameter-seeding
        # block — the internal KMeans fit for init_params='kmeans'
        # contributes its own nested spans (visible as the O(R) member
        # seeding cost the r12 sweep notes document).
        with obs_trace.span("seed", strategy=str(self.init_params),
                            k=self.n_components):
            return self._init_params_inner(ds, step_fn, seed)

    def _init_params_inner(self, ds: ShardedDataset, step_fn, seed: int):
        d = ds.d
        k = self.n_components
        if self.means_init is not None:
            means = np.asarray(self.means_init, np.float64)
            if means.shape != (k, d):
                raise ValueError(f"means_init shape {means.shape} != "
                                 f"({k}, {d})")
        else:
            if self.init_params == "random":
                # sklearn 'random' draws random responsibilities; seeding
                # means at random points is the established analogue.
                from kmeans_tpu.models.init import forgy_init
                means = np.asarray(forgy_init(ds, k, seed,
                                              validate=False), np.float64)
            else:
                # Both 'kmeans' and 'k-means++' seed the internal KMeans
                # with D^2 (k-means++) sampling — sklearn's 'kmeans' mode
                # also runs its KMeans with init='k-means++'; 'k-means++'
                # here skips the Lloyd refinement (seeding only).
                from kmeans_tpu.models.kmeans import KMeans
                refine = 20 if self.init_params == "kmeans" else 1
                km = KMeans(k=k, seed=seed, init="kmeans++",
                            max_iter=refine, verbose=False,
                            compute_labels=False, mesh=self.mesh,
                            empty_cluster="resample")
                km._eager_labels = False
                km.fit(ds)
                means = np.asarray(km.centroids, np.float64)

        # One HARD-assignment E-step (precision >> data scale makes the
        # softmax one-hot) yields the per-component one-hot statistics
        # sklearn also inits from; M-step below turns them into
        # weights/covariances.  Explicit precisions/weights_init override.
        mesh = self._resolve_mesh()
        shift = self._shift()
        hard = step_fn(ds.points, ds.weights,
                       *self._hard_tables(mesh, means, shift))
        w_total, (pi, mu_c, var) = self._m_step(self._trim(hard))
        self.means_ = (mu_c + shift) if self.means_init is None else means
        self.weights_ = (pi if self.weights_init is None
                         else np.asarray(self.weights_init, np.float64))
        self.covariances_ = (self._cov_from_precisions_init()
                             if self.precisions_init is not None else var)
        self.weights_ = self.weights_ / self.weights_.sum()
        return w_total

    def _cov_from_precisions_init(self) -> np.ndarray:
        """Covariances from an explicit ``precisions_init`` (shared by
        the in-memory and streamed init paths)."""
        prec = np.asarray(self.precisions_init, np.float64)
        if self.covariance_type in ("diag", "spherical"):
            return 1.0 / prec
        return np.linalg.inv(prec)      # tied (D,D) / full (k,D,D)

    # ------------------------------------------------------------------- EM

    def _m_step(self, st):
        """float64 host M-step from the psum-reduced E statistics, per
        covariance type (sklearn's update rules).  The inputs are
        CENTERED-frame statistics; the returned means are too (callers
        add the shift back)."""
        R = np.asarray(st.resp_sum, np.float64)
        S1 = np.asarray(st.xsum, np.float64)
        w_total = float(R.sum())
        Rc = np.maximum(R, 10 * np.finfo(np.float64).tiny)
        mu = S1 / Rc[:, None]
        ct = self.covariance_type
        # tiny floors throughout: reg_covar=0 must not leave exact-zero
        # variances (precisions_ would be inf; the compute-dtype floor
        # happens again in _params_dev).
        floor = max(self.reg_covar, np.finfo(np.float64).tiny)
        if ct in ("diag", "spherical"):
            S2 = np.asarray(st.x2sum, np.float64)
            var = S2 / Rc[:, None] - mu ** 2 + self.reg_covar
            var = np.maximum(var, floor)
            if ct == "spherical":
                var = var.mean(axis=1)            # (k,) sklearn shape
        elif ct == "full":
            T = np.asarray(st.scatter, np.float64)          # (k, D, D)
            var = T / Rc[:, None, None] - mu[:, :, None] * mu[:, None, :]
            d = mu.shape[1]
            var[:, np.arange(d), np.arange(d)] += self.reg_covar
            var[:, np.arange(d), np.arange(d)] = np.maximum(
                var[:, np.arange(d), np.arange(d)], floor)
        else:                                     # tied
            # sklearn rule: (total scatter - sum_k R_k mu_k mu_k^T) / W.
            T = self._total_scatter                         # (D, D)
            var = (T - np.einsum("k,kd,ke->de", R, mu, mu)) \
                / max(w_total, 1e-300)
            d = mu.shape[1]
            var[np.arange(d), np.arange(d)] += self.reg_covar
            var[np.arange(d), np.arange(d)] = np.maximum(
                var[np.arange(d), np.arange(d)], floor)
        pi = np.maximum(R / max(w_total, 1e-300), 1e-300)
        return w_total, (pi / pi.sum(), mu, var)

    def fit(self, X, sample_weight=None, *, resume=False,
            checkpoint_every: int = 0,
            checkpoint_path=None) -> "GaussianMixture":
        """Fit by EM.  ``resume=True`` continues EM from the CURRENT
        fitted parameters for up to ``max_iter`` further iterations
        (sklearn's ``warm_start`` capability; composes with
        ``save``/``load`` like ``KMeans.fit(resume=True)``) — single
        restart only, since the restart sweep re-initializes.  Resumed
        trajectories match the uninterrupted fit to fp rounding at
        exact-dot precision (CPU, or TPU with
        ``jax_default_matmul_precision='highest'``); under default
        bf16-rate TPU dots borderline responsibilities can diverge the
        two trajectories percent-level on overlapping clusters — the
        same documented class as the streamed-vs-in-memory comparison.

        Fault tolerance (ISSUE 4): ``resume`` may be a checkpoint PATH
        (loaded with the ``.prev`` corrupt fallback), and
        ``checkpoint_every=N`` + ``checkpoint_path`` auto-checkpoints
        every N EM iterations with the rotating atomic writer — the
        one-dispatch device loop becomes segmented (the convergence
        baseline rides the dispatch as a traced argument and the raw
        centered-frame tables are checkpointed, so both segmentation
        AND kill+resume are bit-exact against the ``checkpoint_every=0``
        oracle; the float64 host loop is bit-exact through its fitted
        attributes alone).  Requires ``n_init=1``."""
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        self.cov_jitter_retries_ = 0
        resume = self._resolve_resume(resume)
        ds = self._staged_dataset(X, sample_weight)
        self.io_retries_used_ = getattr(
            getattr(ds, "io_stats", None), "retries_used", 0)
        mesh = self._resolve_mesh()
        # Fleet prelude (ISSUE 13): rows for heartbeat rows_per_sec +
        # the merged-timeline clock anchor (no-op when obs=0).
        self._progress_rows = ds.local_rows if getattr(
            ds, "local_rows", None) else ds.n
        fleet_barrier("fit-start")
        chunk = self._eff_chunk(ds)
        pipeline = self._note_estep_path()
        step_fn, _ = _get_fns(mesh, chunk, self.covariance_type, pipeline)
        self._fit_chunk = chunk
        # Centering shift: the dataset's weighted global mean (see module
        # docstring).  One cheap GSPMD pass, fixed for the whole fit.
        self.shift_ = np.asarray(
            _mean_jit(ds.points, ds.weights), np.float64)
        if self.covariance_type == "tied":
            # The tied M-step's total scatter is loop-INVARIANT (it only
            # depends on the data and the shift) — one pass per fit.
            ts_fn = _STEP_CACHE.get_or_create(
                (mesh, "gmm_total_scatter"),
                lambda: make_total_scatter_fn(mesh))
            self._total_scatter = np.asarray(
                ts_fn(ds.points, ds.weights,
                      jnp.asarray(self.shift_.astype(self.dtype))),
                np.float64)
        if resume and self.means_ is not None:
            if self.n_init != 1:
                raise ValueError("fit(resume=True) requires n_init == 1 "
                                 "(the restart sweep re-initializes)")
            self._fit_one(ds, mesh, step_fn, self.seed, resume=True,
                          checkpoint_every=checkpoint_every,
                          checkpoint_path=checkpoint_path)
            return self
        seeds = self._restart_seeds()
        self.best_restart_ = 0
        self.restart_lower_bounds_ = None

        # Batched restart sweep: ALL n_init restarts vmapped through ONE
        # EM dispatch (the mixture analogue of KMeans' make_multi_fit_fn;
        # diag/spherical — the batchable density).
        if len(seeds) > 1 and not self.host_loop \
                and self.covariance_type in ("diag", "spherical"):
            return self._fit_on_device_multi(ds, mesh, step_fn, seeds)

        best = None
        lls = []
        last_err = None
        for r, seed in enumerate(seeds):
            try:
                self._fit_one(ds, mesh, step_fn, seed,
                              checkpoint_every=checkpoint_every,
                              checkpoint_path=checkpoint_path)
            except Exception as e:
                # A failed restart (e.g. the device loop's non-finite-
                # loglik error) must not discard earlier successful
                # restarts or leave the model holding the failed
                # restart's partial state (r3 ADVICE).  Single-restart
                # fits still propagate immediately.
                if len(seeds) == 1:
                    raise
                import warnings
                warnings.warn(f"GMM restart {r + 1}/{len(seeds)} failed "
                              f"({e}); continuing with the remaining "
                              f"restarts", UserWarning, stacklevel=2)
                last_err = e
                lls.append(-np.inf)
                continue
            if len(seeds) == 1:
                return self
            lls.append(self.lower_bound_)
            if best is None or self.lower_bound_ > best["ll"]:
                # The raw device tables travel WITH the winner: restoring
                # only the sklearn-frame attrs would leave _dev_tables
                # holding the LAST restart's carry, and a later
                # save()+resume would silently continue a losing
                # trajectory (review r9).
                best = {"ll": self.lower_bound_, "restart": r,
                        "weights_": self.weights_, "means_": self.means_,
                        "covariances_": self.covariances_,
                        "converged_": self.converged_,
                        "n_iter_": self.n_iter_,
                        "_dev_tables": self._dev_tables}
        if best is None:
            raise last_err
        self.weights_ = best["weights_"]
        self.means_ = best["means_"]
        self.covariances_ = best["covariances_"]
        self.converged_ = best["converged_"]
        self.n_iter_ = best["n_iter_"]
        self.lower_bound_ = best["ll"]
        self.best_restart_ = best["restart"]
        self.restart_lower_bounds_ = np.asarray(lls, np.float64)
        self._dev_tables = best["_dev_tables"]
        return self

    def fit_stream(self, make_blocks, *, d: Optional[int] = None,
                   resume=False, prefetch: int = 2,
                   checkpoint_every: int = 0, checkpoint_path=None,
                   io_retries: int = 0, io_backoff: float = 0.05,
                   on_nonfinite: str = "error") -> "GaussianMixture":
        """EXACT EM over data larger than device memory — the mixture
        analogue of ``KMeans.fit_stream`` (r3 VERDICT #6: the E-step
        statistics are the same dense host-summable accumulators the
        K-Means streaming path already sums).

        ``make_blocks()`` returns a fresh iterable of (n_i, D) host
        blocks — or ``(block, weights)`` pairs, folding weights into
        every statistic like ``fit``'s ``sample_weight`` — re-invoked
        every EM iteration (one epoch = one exact E-step; the float64
        host M-step is unchanged), so the trajectory matches an
        in-memory ``fit`` of the concatenated blocks up to fp summation
        order.  ``n_init`` restarts run INTERLEAVED — every
        epoch computes all live restarts' statistics from one shared
        pass (R x compute, 1x IO) — and the winner is the restart with
        the highest final ``lower_bound_``, the in-memory selection
        rule.  Exception: ``init_params='kmeans'`` refines each
        restart's seeds with its OWN ~20-epoch streamed Lloyd fit (R x
        the IO, the one phase that does not share passes) — on IO-bound
        streams prefer ``'k-means++'`` (seeding only) or an explicit
        ``means_init``.

        Setup passes before the EM epochs: one for the centering shift
        (+ one for the tied total scatter), the init strategy's passes
        (``means_init`` none; ``init_params='random'`` one reservoir
        pass; ``'k-means++'`` a streamed kmeans||; ``'kmeans'``
        additionally ~20 streamed Lloyd epochs — pass explicit
        ``means_init`` to skip), and one hard-assignment epoch for the
        initial responsibilities.

        ``prefetch`` (default 2): every data pass (centering, tied
        scatter, hard-assignment, EM epochs) stages the next block's
        read + decode + device placement in a bounded background
        producer while the current block computes
        (``data.prefetch.prefetch_iter`` — the same machinery and
        bit-identical-trajectory contract as ``KMeans.fit_stream``);
        0 = the synchronous path.  The streamed init passes stay
        synchronous (once per fit; their reservoir state is
        consumption-order-bound anyway).

        Fault tolerance (ISSUE 4, matching ``KMeans.fit_stream``):
        ``resume`` (bool or checkpoint path; requires ``n_init=1``)
        continues EM from the current parameters for up to ``max_iter``
        further epochs; ``checkpoint_every=N`` + ``checkpoint_path``
        writes a rotating atomic checkpoint every N epochs;
        ``io_retries``/``io_backoff`` retry transient block reads by
        deterministic epoch replay; ``on_nonfinite='error'|'skip'``
        names or quarantines non-finite streamed blocks (every pass —
        shift, scatter, init, EM — sees the same cleaned stream).
        Observability: ``io_retries_used_``, ``blocks_skipped_``,
        ``checkpoint_segments_``.
        """
        from kmeans_tpu.data.io import IOStats, resilient_blocks
        from kmeans_tpu.data.prefetch import (check_prefetch, close_source,
                                              prefetch_iter)
        from kmeans_tpu.parallel.sharding import shard_points
        from kmeans_tpu.models.init import (_split_block,
                                            streamed_forgy_init,
                                            streamed_kmeans_parallel_init)
        prefetch = check_prefetch(prefetch)
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        self.cov_jitter_retries_ = 0
        resume = self._resolve_resume(resume) and self.means_ is not None
        if resume and self.n_init != 1:
            raise ValueError("fit_stream resume requires n_init == 1")
        io_stats = IOStats()
        make_blocks = resilient_blocks(
            make_blocks, io_retries=io_retries, io_backoff=io_backoff,
            on_nonfinite=on_nonfinite, stats=io_stats)
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        if d is None:
            # close_source: a prefetching source must have its producer
            # thread reaped when the peek abandons it after one item.
            peek_it = iter(make_blocks())
            try:
                item = next(peek_it)
            except StopIteration:
                raise ValueError(
                    "make_blocks() yielded no rows — it must return a "
                    "FRESH iterable on every call") from None
            finally:
                close_source(peek_it)
            peek = np.asarray(item[0] if isinstance(item, tuple) else item,
                              dtype=self.dtype)
            if peek.ndim != 2:
                raise ValueError(f"blocks must be 2-D (m, D), got shape "
                                 f"{peek.shape}")
            d = peek.shape[1]
            del peek, item
        mesh = self._resolve_mesh()
        # Fleet prelude (ISSUE 13): clock anchor; streamed EM has no
        # fixed per-iteration row count until an epoch has run, so
        # rows_per_sec stays absent (documented) — the anchor is what
        # the merged timeline needs.
        self._progress_rows = None
        fleet_barrier("fit-stream-start")
        ct = self.covariance_type
        k = self.n_components
        pipeline = self._note_estep_path()

        # ---- pass: weighted centering shift (+ positive-row count) in
        # float64 on the host.  Items may be (block, weights) pairs —
        # weights fold into every statistic like fit's sample_weight.
        sx = np.zeros(d)
        sw_total = 0.0
        n_rows = n_pos = 0
        with contextlib.closing(prefetch_iter(
                make_blocks(), prefetch,
                lambda item: _split_block(item, d, np.float64))) as it:
            for block, bw in it:
                n_rows += block.shape[0]
                if bw is None:
                    sx += block.sum(axis=0)
                    sw_total += block.shape[0]
                    n_pos += block.shape[0]
                else:
                    sx += (block * bw[:, None]).sum(axis=0)
                    sw_total += float(bw.sum())
                    n_pos += int((bw > 0).sum())
        if n_rows == 0:
            raise ValueError("make_blocks() yielded no rows — it must "
                             "return a FRESH iterable on every call")
        if n_pos == 0:          # rows exist but every weight is zero
            raise ValueError("total sample weight must be positive")
        if n_pos < k:
            raise ValueError(f"Not enough data points ({n_pos}) to "
                             f"initialize {k} clusters")
        self.shift_ = sx / sw_total
        shift = self.shift_

        chunk = self.chunk_size
        step_fn = None

        def stage_block(item):
            """Producer-side share of one block (background thread when
            ``prefetch > 0``): decode + pad + device placement, so block
            i+1's IO/transfer overlaps block i's E-pass.  Chunk is sized
            from the FIRST real block; the queue hand-off publishes it
            to the consumer before the staged block arrives."""
            nonlocal chunk
            block, bw = _split_block(item, d, self.dtype)
            if chunk is None:
                data_shards, _ = mesh_shape(mesh)
                eff_k = k * d if ct == "full" else k
                chunk = choose_chunk_size(
                    -(-block.shape[0] // data_shards), eff_k, d,
                    budget_elems=EM_CHUNK_BUDGET)
            pts, w = shard_points(block, mesh, chunk, sample_weight=bw)
            return pts, w

        def epoch_stats(tables_list):
            """One pass accumulating each table set's E statistics in
            float64 on the host.  ``tables_list`` holds per-restart
            step arguments (post points/weights)."""
            nonlocal step_fn
            acc = [None] * len(tables_list)
            with contextlib.closing(prefetch_iter(
                    make_blocks(), prefetch, stage_block)) as it:
                for pts, w in it:
                    if step_fn is None:
                        step_fn = _get_fns(mesh, chunk, ct, pipeline)[0]
                    outs = [step_fn(pts, w, *t) for t in tables_list]
                    for i, st in enumerate(outs):
                        st = jax.device_get(st)
                        tr = self._trim(st)
                        tr = type(tr)(*[np.asarray(f, np.float64)
                                        if np.ndim(f) else float(f)
                                        for f in tr])
                        acc[i] = tr if acc[i] is None else type(tr)(
                            *[a + b for a, b in zip(acc[i], tr)])
            if acc[0] is None:
                raise ValueError(
                    "make_blocks() yielded no rows — it must return a "
                    "FRESH iterable on every call (one epoch per EM "
                    "iteration)")
            return acc

        if ct == "tied":
            # Loop-invariant total scatter, accumulated per block.
            ts_fn = _STEP_CACHE.get_or_create(
                (mesh, "gmm_total_scatter"),
                lambda: make_total_scatter_fn(mesh))
            T = np.zeros((d, d))

            def stage_scatter(item):
                block, bw = _split_block(item, d, self.dtype)
                return shard_points(
                    block, mesh, chunk or choose_chunk_size(
                        -(-block.shape[0] // mesh_shape(mesh)[0]), k, d,
                        budget_elems=EM_CHUNK_BUDGET),
                    sample_weight=bw)

            shift_dev = jnp.asarray(shift.astype(self.dtype))
            with contextlib.closing(prefetch_iter(
                    make_blocks(), prefetch, stage_scatter)) as it:
                for pts, w in it:
                    T += np.asarray(ts_fn(pts, w, shift_dev), np.float64)
            self._total_scatter = T

        class _RS:
            def __init__(self):
                self.done = False
                self.failed = False
                self.prev = -np.inf
                self.ll = -np.inf
                self.n_iter = 0

        if resume:
            # Continue EM from the current float64 parameters: the
            # stream passes above re-derive shift/scatter exactly (same
            # deterministic stream), the restored ``lower_bound_`` is
            # the convergence baseline, and the epoch index continues
            # from ``n_iter_`` — so an epoch-boundary kill+resume runs
            # the identical per-epoch trajectory.  Like fit(resume=True)
            # the resumed call grants max_iter FURTHER epochs, so the
            # final state is bit-identical to the uninterrupted fit
            # whenever that fit converges within its own budget (the
            # case the parity tests pin); a budget-exhausted fit resumes
            # with fresh headroom instead.
            base_iter = self.n_iter_
            params = [(np.asarray(self.weights_, np.float64),
                       np.asarray(self.means_, np.float64),
                       np.asarray(self.covariances_, np.float64))]
            states = [_RS()]
            states[0].prev = self.lower_bound_
            states[0].ll = self.lower_bound_
            states[0].n_iter = base_iter
            return self._fit_stream_epochs(
                mesh, shift, params, states, base_iter, epoch_stats,
                io_stats, checkpoint_every, checkpoint_path)

        base_iter = 0
        # ---- per-restart means over the FULL stream.
        seeds = self._restart_seeds()
        if self.means_init is not None:
            means = np.asarray(self.means_init, np.float64)
            if means.shape != (k, d):
                raise ValueError(f"means_init shape {means.shape} != "
                                 f"({k}, {d})")
            means_list = [means]
            seeds = seeds[:1]
        elif self.init_params == "random":
            outs, _ = streamed_forgy_init(make_blocks, k, seeds, d,
                                          self.dtype)
            means_list = [np.asarray(m, np.float64) for m in outs]
        else:
            outs, _ = streamed_kmeans_parallel_init(make_blocks, k, seeds,
                                                    d, self.dtype)
            means_list = [np.asarray(m, np.float64) for m in outs]
            if self.init_params == "kmeans":
                # Lloyd refinement over the stream (the in-memory path
                # refines its seeds with 20 Lloyd iterations too).
                from kmeans_tpu.models.kmeans import KMeans
                refined = []
                for m, s in zip(means_list, seeds):
                    # empty_cluster='resample' matches the in-memory init
                    # path's internal KMeans (review r4 — 'keep' would
                    # pin a dead seed the in-memory fit resamples).
                    km = KMeans(k=k, seed=s, init=m.astype(self.dtype),
                                max_iter=20, verbose=False,
                                mesh=mesh, compute_labels=False,
                                empty_cluster="resample")
                    km.fit_stream(make_blocks, d=d, prefetch=prefetch)
                    refined.append(np.asarray(km.centroids, np.float64))
                means_list = refined

        # ---- hard-assignment epoch -> per-restart initial params.
        hard_tables = [self._hard_tables(mesh, m, shift)
                       for m in means_list]
        hard_stats = epoch_stats(hard_tables)

        states = [_RS() for _ in means_list]
        params = []
        w_total0 = None
        for m, st in zip(means_list, hard_stats):
            w_total0, (pi, mu_c, var) = self._m_step(st)
            mu = (mu_c + shift) if self.means_init is None else m
            if self.weights_init is not None:
                pi = np.asarray(self.weights_init, np.float64)
                pi = pi / pi.sum()
            if self.precisions_init is not None:
                var = self._cov_from_precisions_init()
            params.append((pi, mu, var))
        if w_total0 is not None and w_total0 <= 0:
            raise ValueError("total sample weight must be positive")

        return self._fit_stream_epochs(
            mesh, shift, params, states, base_iter, epoch_stats,
            io_stats, checkpoint_every, checkpoint_path)

    def _fit_stream_epochs(self, mesh, shift, params, states, base_iter,
                           epoch_stats, io_stats, checkpoint_every,
                           checkpoint_path) -> "GaussianMixture":
        """The interleaved exact-EM epoch loop + winner selection shared
        by fresh and resumed ``fit_stream`` runs.  ``base_iter`` offsets
        the epoch index (absolute, so checkpoint cadence and restored
        convergence baselines continue the uninterrupted schedule)."""
        last_err = None

        def fail_restart(i, err):
            """Same restart resilience as fit() (r3 ADVICE): a failing
            restart is dropped with a warning instead of aborting the
            healthy ones; single-restart failures propagate."""
            nonlocal last_err
            if len(states) == 1:
                raise err
            import warnings
            warnings.warn(f"GMM restart {i + 1}/{len(states)} failed "
                          f"({err}); continuing with the remaining "
                          f"restarts", UserWarning, stacklevel=3)
            states[i].failed = states[i].done = True
            states[i].ll = -np.inf
            last_err = err

        # ---- interleaved exact-EM epochs.
        for it in range(base_iter + 1, base_iter + self.max_iter + 1):
            live = []
            tables = []
            for i, s in enumerate(states):
                if s.done:
                    continue
                pi, mu, var = params[i]
                self.weights_, self.means_ = pi, mu
                self.covariances_ = var
                try:
                    tables.append(self._params_dev(mesh,
                                                   guard_cholesky=True))
                except Exception as e:      # e.g. singular full/tied cov
                    fail_restart(i, e)
                    continue
                live.append(i)
            if not live:
                break
            t0 = time.perf_counter()
            stats = epoch_stats(tables)
            for j, i in enumerate(live):
                st = states[i]
                w_total, (pi, mu_c, var) = self._m_step(stats[j])
                params[i] = (pi, mu_c + shift, var)
                st.ll = float(stats[j].loglik) / w_total
                st.n_iter = it
                # Narrate the lowest LIVE restart, not restart 0 — the
                # log must not go silent while later restarts still run
                # epochs (review r4).
                if self.verbose and i == live[0]:
                    print(f"EM iteration {it}: mean log-likelihood = "
                          f"{st.ll:.6f} "
                          f"[{(time.perf_counter() - t0) * 1e3:.1f} ms]",
                          flush=True)
                if not np.isfinite(st.ll):
                    if len(states) == 1:
                        # Single restart (the only checkpointable
                        # configuration): divergence-rollback exit.
                        self._raise_divergence("log-likelihood", it)
                    fail_restart(i, ValueError(
                        f"non-finite log-likelihood at EM iteration "
                        f"{it}"))
                    continue
                if abs(st.ll - st.prev) < self.tol:
                    st.done = True
                st.prev = st.ll
            # Epoch-boundary rotating checkpoint (single-restart only,
            # enforced by _check_ckpt): publish the post-epoch params so
            # the checkpoint is a valid bit-exact resume point.
            if checkpoint_every and it % checkpoint_every == 0 \
                    and not states[0].failed:
                pi, mu, var = params[0]
                self.weights_, self.means_, self.covariances_ = \
                    pi, mu, var
                self.lower_bound_ = states[0].ll
                self.converged_ = states[0].done
                self.n_iter_ = states[0].n_iter
                self._dev_tables = None      # float64 host-frame carry
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, it)

        # ---- winner (highest final lower bound, the in-memory rule).
        if all(s.failed for s in states):
            raise last_err
        lls = [s.ll for s in states]
        best = int(np.argmax(lls))
        pi, mu, var = params[best]
        self.weights_, self.means_, self.covariances_ = pi, mu, var
        self.lower_bound_ = states[best].ll
        self.converged_ = states[best].done
        self.n_iter_ = states[best].n_iter
        self.best_restart_ = best
        self.restart_lower_bounds_ = (np.asarray(lls, np.float64)
                                      if len(states) > 1 else None)
        self._dev_tables = None
        self.io_retries_used_ = io_stats.retries_used
        self.blocks_skipped_ = io_stats.blocks_skipped
        if checkpoint_every and self.n_iter_ % checkpoint_every:
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.n_iter_)
        return self

    def _fit_one(self, ds, mesh, step_fn, seed: int,
                 resume: bool = False, checkpoint_every: int = 0,
                 checkpoint_path=None) -> None:
        if not resume:
            # Continue-from-current (resume) skips the re-init; the
            # iteration counter carries over on both loops, and the
            # convergence baseline carries over on both too (the device
            # kernel receives it as the traced ``prev0`` argument —
            # ISSUE 4 made the device resume exact, not one-extra-
            # iteration approximate).
            w_total = self._init_params(ds, step_fn, seed)
            if w_total <= 0:
                raise ValueError("total sample weight must be positive")
        if not self.host_loop:
            return self._fit_on_device(
                ds, mesh, base_iter=self.n_iter_ if resume else 0,
                resume=resume, checkpoint_every=checkpoint_every,
                checkpoint_path=checkpoint_path)

        self.converged_ = False
        # The float64 host loop's exact carry IS its fitted attributes;
        # stale raw device tables must not survive into its checkpoints.
        self._dev_tables = None
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        base = self.n_iter_ if resume else 0
        prev = self.lower_bound_ if resume else -np.inf
        shift = self._shift()
        for it in range(base + 1, base + self.max_iter + 1):
            t0 = time.perf_counter()
            # The 'dispatch' span covers dispatch + the M-step that
            # materializes the statistics (JAX dispatch is async; the
            # host sync happens inside _m_step's array reads).
            with obs_trace.span("dispatch", tag="em/step", iteration=it):
                st: EStats = step_fn(ds.points, ds.weights,
                                     *self._params_dev(
                                         mesh, guard_cholesky=True))
                # The per-iteration float64 M-step total (sum of resp
                # sums == total sample weight) normalizes the lower
                # bound — the same reduction class on fresh AND resumed
                # fits (an f32 device-side sum diverged from it at
                # large n, review r4).
                w_total, (pi, mu_c, var) = self._m_step(self._trim(st))
            if w_total <= 0:
                raise ValueError("total sample weight must be positive")
            self.weights_, self.means_ = pi, mu_c + shift
            self.covariances_ = var
            self.lower_bound_ = float(st.loglik) / w_total
            self.n_iter_ = it
            if self.verbose:
                print(f"EM iteration {it}: mean log-likelihood = "
                      f"{self.lower_bound_:.6f} "
                      f"[{(time.perf_counter() - t0) * 1e3:.1f} ms]",
                      flush=True)
            if not np.isfinite(self.lower_bound_):
                # Divergence-rollback exit (ISSUE 5): restore the
                # last-good checkpoint (when active) before raising.
                self._raise_divergence("log-likelihood", it)
            # Heartbeat (ISSUE 11): the EM host loop already
            # materialized this iteration's state — zero extra
            # dispatches for the progress record.
            obs_note_progress(self, phase="iteration")
            # Absolute-index cadence (after the non-finite guard: never
            # checkpoint a poisoned state).
            if checkpoint_every and it % checkpoint_every == 0:
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, it)
            if abs(self.lower_bound_ - prev) < self.tol:
                self.converged_ = True
                break
            prev = self.lower_bound_
        if checkpoint_every and self.n_iter_ % checkpoint_every:
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.n_iter_)

    def _fit_on_device_multi(self, ds, mesh, step_fn,
                             seeds) -> "GaussianMixture":
        """All ``n_init`` restarts in ONE dispatch (diag/spherical): each
        restart's hard-assignment init runs host-side (R cheap passes),
        then the (R, k_pad, ...) parameter tables ride one vmapped
        device EM loop; the winner — highest final lower bound, the
        host-sequential selection rule — comes back selected on device."""
        ct = self.covariance_type
        R = len(seeds)
        k, k_pad = self.n_components, self._k_pad
        d = ds.d
        means0 = np.zeros((R, k_pad, d), self.dtype)
        var0 = np.ones((R, k_pad, d), self.dtype)
        log_w0 = np.full((R, k_pad), -np.inf, self.dtype)
        shift = self._shift()
        # Per-restart init failures keep the survivors (the sequential
        # path's r3-ADVICE resilience, covering host-side init errors as
        # well as the kernel's in-loop divergence masking); only the
        # SURVIVING rows ride the batched dispatch, and indices map back
        # to the original restart numbering below.
        alive = []
        init_err = None
        for r, seed in enumerate(seeds):
            try:
                w_total = self._init_params(ds, step_fn, seed)
                if w_total <= 0:
                    raise ValueError(
                        "total sample weight must be positive")
            except Exception as e:
                if R == 1:
                    raise
                import warnings
                warnings.warn(f"GMM restart {r + 1}/{R} failed at init "
                              f"({e}); continuing with the remaining "
                              f"restarts", UserWarning, stacklevel=2)
                init_err = e
                continue
            i = len(alive)
            alive.append(r)
            means0[i, :k] = (self.means_ - shift).astype(self.dtype)
            var0[i, :k] = np.maximum(
                self._diag_view(),
                max(self.reg_covar,
                    float(np.finfo(self.dtype).tiny))).astype(self.dtype)
            log_w0[i, :k] = np.log(
                np.maximum(self.weights_, 1e-300)).astype(self.dtype)
        if not alive:
            raise init_err
        if len(alive) < R:
            means0 = means0[: len(alive)]
            var0 = var0[: len(alive)]
            log_w0 = log_w0[: len(alive)]
        R_live = len(alive)
        chunk = self._eff_chunk(ds)
        pipeline = self._note_estep_path()
        key = (mesh, chunk, k, self.max_iter, float(self.tol),
               float(self.reg_covar), ct, R_live, pipeline, "gmmmultifit")
        fit_fn = _STEP_CACHE.get_or_create(
            key, lambda: make_gmm_multi_fit_fn(
                mesh, chunk_size=chunk, k_real=k,
                max_iter=self.max_iter, tol=float(self.tol),
                reg_covar=float(self.reg_covar), cov_type=ct,
                pipeline=pipeline))
        means_out, var_out, log_w_out, n_it, hist, conv, best, lls = \
            fit_fn(ds.points, ds.weights,
                   jnp.asarray(shift.astype(self.dtype)),
                   jnp.asarray(means0), jnp.asarray(var0),
                   jnp.asarray(log_w0))
        # Map survivor-row results back to the ORIGINAL restart
        # numbering (init-failed restarts hold -inf).
        lls_live = np.asarray(lls, np.float64)
        lls = np.full((R,), -np.inf)
        lls[np.asarray(alive)] = lls_live
        best = alive[int(best)]
        # Diverged restarts surface as -inf and cannot win (the
        # sequential path's failed-restart resilience, r3 ADVICE);
        # raising is reserved for EVERY restart diverging.
        if not np.any(np.isfinite(lls)):
            raise ValueError(
                "non-finite log-likelihood in every batched restart")
        n_failed = int(np.sum(~np.isfinite(lls_live)))
        if n_failed:
            import warnings
            warnings.warn(f"{n_failed} of {R_live} batched GMM restarts "
                          f"diverged (non-finite log-likelihood); "
                          f"continuing with the survivors", UserWarning,
                          stacklevel=2)
        n = int(n_it)
        hist = np.asarray(hist, np.float64)[:n]
        if n and not np.all(np.isfinite(hist)):
            raise ValueError(
                f"non-finite log-likelihood at EM iteration {n}")
        self.means_ = np.asarray(means_out, np.float64)[:k] + shift
        cv_out = np.asarray(var_out, np.float64)
        self.covariances_ = (cv_out[:k, 0] if ct == "spherical"
                             else cv_out[:k])
        w = np.exp(np.asarray(log_w_out, np.float64)[:k])
        self.weights_ = w / w.sum()
        self.converged_ = bool(conv)
        self.n_iter_ = n
        self.lower_bound_ = float(hist[-1]) if n else -np.inf
        self.best_restart_ = int(best)
        self.restart_lower_bounds_ = np.asarray(lls, np.float64)
        self._dev_tables = None     # no single-trajectory carry to keep
        if self.verbose:
            print(f"EM batched restarts: best {self.best_restart_ + 1} of "
                  f"{R}, mean log-likelihood = {self.lower_bound_:.6f}",
                  flush=True)
        return self

    # ----------------------------------------------------------------- sweep

    def sweep(self, X, *, k_range, criterion: str = "bic",
              sample_weight=None, batched=True):
        """Component-count selection: fit every (k, restart) member,
        score by ``criterion`` ('bic' | 'aic', minimized), return a
        :class:`~kmeans_tpu.sweep.SweepResult` (ISSUE 7 tentpole — the
        mixture half of the batched k sweep).

        ``batched=True`` pads every member to k_max with the r10 inert
        components (zero mean, unit variance, -inf log-weight — the same
        constants topology-portable checkpoints pad with) and runs the
        whole sweep as ONE vmapped EM dispatch
        (`parallel.gmm_step.make_gmm_multi_fit_fn` with a per-member k
        axis) plus one fused fresh-scoring pass of every member's FINAL
        parameters — the quantity ``bic``/``aic`` is defined on (the
        in-loop lower bound lags one M-step).  Member SEEDING is outside
        that economy: the default ``init_params='kmeans'`` runs a short
        per-member device KMeans refinement (O(R) dispatches, identical
        on both paths — it is what the oracle parity is pinned against);
        on dispatch-latency-bound links prefer ``init_params='random'``,
        which seeds without per-member fits.  Batching needs the
        diag/spherical density; 'full'/'tied' fall back to the
        sequential path with a warning.  ``batched=0`` is the
        sequential per-member oracle (one device-loop EM fit + one
        ``bic``/``aic`` pass per member on the same cached dataset) the
        batched members must match to the documented GMM reduction
        class.  Within each k the winning restart is the highest final
        lower bound (the family's ``n_init`` rule); the criterion then
        selects across k.  Batched BIC uses the WEIGHTED mean
        log-likelihood (== ``score`` on unweighted data up to the
        reduction class).  Requires ``means_init=None`` (an explicit
        init pins k)."""
        from kmeans_tpu import sweep as sweep_mod
        from kmeans_tpu.utils import profiling

        if self.means_init is not None or self.precisions_init is not None \
                or self.weights_init is not None:
            raise ValueError("sweep() needs data-driven inits (explicit "
                             "means/weights/precisions pin k)")
        ks = sweep_mod.parse_k_range(k_range)
        sweep_mod.check_criterion(criterion, sweep_mod.GMM_CRITERIA)
        k_max = ks[-1]
        ct = self.covariance_type
        if batched and ct not in ("diag", "spherical"):
            import warnings
            warnings.warn(
                f"batched GMM sweep needs the diag/spherical density; "
                f"covariance_type={ct!r} runs the sequential path",
                UserWarning, stacklevel=2)
            batched = False

        engine = sweep_mod.clone_for(self, n_components=k_max,
                                     verbose=False)
        ds = engine._dataset(X, sample_weight)
        if k_max >= ds.n:
            raise ValueError(f"k_max={k_max} must be < n={ds.n}")
        mesh = engine._resolve_mesh()
        chunk = engine._eff_chunk(ds)
        pipeline = engine._note_estep_path()
        step_fn, _ = _get_fns(mesh, chunk, ct, pipeline)
        engine.shift_ = np.asarray(
            _mean_jit(ds.points, ds.weights), np.float64)
        shift = engine._shift()
        seeds = engine._restart_seeds()
        members = [(k, s) for k in ks for s in seeds]
        R, n_init = len(members), len(seeds)
        n = ds.n
        d = ds.d
        n_disp = 0

        if batched:
            k_pad = engine._k_pad
            means0 = np.zeros((R, k_pad, d), self.dtype)
            var0 = np.ones((R, k_pad, d), self.dtype)
            log_w0 = np.full((R, k_pad), -np.inf, self.dtype)
            # Member seeding is OUTSIDE the one-dispatch economy (same
            # convention as the K-Means sweep's per-member
            # _init_centroids): with the default init_params='kmeans'
            # each member's _init_params runs a short per-member device
            # KMeans refinement, so seeding costs O(R) dispatches even
            # on the batched path — visible under log_dispatches below,
            # excluded from ``n_dispatches`` (which counts the
            # amortized fit+scoring work).  init_params='random' seeds
            # without the per-member fits.
            heavy_init = (self.means_init is None
                          and self.init_params != "random")
            for i, (k_m, s) in enumerate(members):
                gm = sweep_mod.clone_for(self, n_components=k_m, seed=s,
                                         n_init=1, verbose=False)
                gm.mesh = mesh
                gm.shift_ = engine.shift_
                if heavy_init:
                    profiling.note_dispatch("sweep/member-init")
                w_total = gm._init_params(ds, step_fn, s)
                if w_total <= 0:
                    raise ValueError(
                        "total sample weight must be positive")
                means0[i, :k_m] = (gm.means_ - shift).astype(self.dtype)
                var0[i, :k_m] = np.maximum(
                    gm._diag_view(),
                    max(self.reg_covar,
                        float(np.finfo(self.dtype).tiny))
                ).astype(self.dtype)
                log_w0[i, :k_m] = np.log(
                    np.maximum(gm.weights_, 1e-300)).astype(self.dtype)
            member_ks = tuple(k for k, _ in members)
            # The batched EM scan materializes an (R, chunk, k_pad)
            # responsibilities tile — R times the single-model tile
            # ``_eff_chunk`` budgeted ``chunk`` for.  Clamp by the
            # member-scaled width (the K-Means sweep's measured-1.9x
            # cache-blowout rule applied to the EM budget); explicit
            # user chunks pass through untouched, and GMM member
            # parity is the documented reduction class either way.
            sweep_chunk = ds.effective_chunk(R * k_max, EM_CHUNK_BUDGET,
                                             max_chunk=EM_MAX_CHUNK)
            key = (mesh, sweep_chunk, k_max, member_ks, self.max_iter,
                   float(self.tol), float(self.reg_covar), ct, pipeline,
                   "gmmsweep")
            fit_fn = _STEP_CACHE.get_or_create(
                key, lambda: make_gmm_multi_fit_fn(
                    mesh, chunk_size=sweep_chunk, k_real=k_max,
                    max_iter=self.max_iter, tol=float(self.tol),
                    reg_covar=float(self.reg_covar), cov_type=ct,
                    pipeline=pipeline, k_reals=member_ks,
                    return_all=True))
            profiling.note_dispatch("sweep/fit")
            means, var, log_w, n_it, hist, conv, flls, fscores = fit_fn(
                ds.points, ds.weights,
                jnp.asarray(shift.astype(self.dtype)),
                jnp.asarray(means0), jnp.asarray(var0),
                jnp.asarray(log_w0))
            n_disp += 1
            means = np.asarray(means, np.float64)
            var = np.asarray(var, np.float64)
            log_w = np.asarray(log_w, np.float64)
            n_it = np.asarray(n_it)
            conv = np.asarray(conv)
            flls = np.asarray(flls, np.float64)
            fscores = np.asarray(fscores, np.float64)
            crit_vals = np.asarray(
                [self._criterion_value(criterion, fscores[i], k_m, d, n)
                 for i, (k_m, _) in enumerate(members)])
            fitted = None
        else:
            flls = np.full((R,), -np.inf)
            crit_vals = np.full((R,), np.inf)
            n_it = np.zeros((R,), np.int64)
            fitted = []
            for i, (k_m, s) in enumerate(members):
                gm = sweep_mod.clone_for(self, n_components=k_m, seed=s,
                                         n_init=1, verbose=False,
                                         host_loop=False)
                gm.mesh = mesh
                profiling.note_dispatch("sweep/member-fit")
                gm.fit(ds)
                n_disp += 1
                flls[i] = gm.lower_bound_
                n_it[i] = gm.n_iter_
                profiling.note_dispatch("sweep/member-score")
                crit_vals[i] = (gm.bic(ds) if criterion == "bic"
                                else gm.aic(ds))
                n_disp += 1
                fitted.append(gm)

        if not np.any(np.isfinite(flls)):
            raise ValueError(
                "non-finite log-likelihood in every sweep member")
        # Within-k winner: highest final lower bound (the n_init rule).
        lls, best_r, win_idx = sweep_mod.within_k_winners(
            flls, len(ks), n_init, maximize=True)
        crit = crit_vals.reshape(len(ks), n_init)
        idx = np.arange(len(ks))
        scores = np.where(np.isfinite(lls[idx, best_r]),
                          crit[idx, best_r], np.inf)

        selected_k, sel, m_sel = sweep_mod.selected_member(
            ks, scores, criterion, win_idx)

        if batched:
            best = sweep_mod.clone_for(self, n_components=selected_k)
            best.mesh = mesh
            best.shift_ = np.asarray(engine.shift_, np.float64)
            best._ingest_device_tables(means[m_sel], var[m_sel],
                                       log_w[m_sel], shift)
            best.converged_ = bool(conv[m_sel])
            best.n_iter_ = int(n_it[m_sel])
            best.lower_bound_ = float(flls[m_sel])
            best._dev_tables = None
        else:
            best = fitted[m_sel]
        best.best_restart_ = int(best_r[sel])
        best.restart_lower_bounds_ = np.asarray(lls[sel], np.float64)

        return sweep_mod.SweepResult(
            family="gmm", criterion=criterion, k_range=ks,
            scores=np.asarray(scores, np.float64),
            member_scores=lls.astype(np.float64),
            selected_k=selected_k, selected_restart=int(best_r[sel]),
            best_model=best, n_dispatches=n_disp, batched=bool(batched),
            n_iters=np.asarray(n_it).reshape(len(ks), n_init))

    def _criterion_value(self, criterion: str, mean_ll: float, k: int,
                         d: int, n: int) -> float:
        """BIC/AIC from a member's mean log-likelihood — the existing
        ``bic``/``aic`` formulas, shape-parameterized for the sweep."""
        if not np.isfinite(mean_ll):
            return np.inf
        pen = self._n_parameters_for(k, d, self.covariance_type)
        if criterion == "bic":
            return -2.0 * mean_ll * n + pen * math.log(n)
        return -2.0 * mean_ll * n + 2.0 * pen

    @staticmethod
    def _pack_dev_tables(ct, means_out, cov_out, log_w_out, prev) -> dict:
        """The raw device-loop carry in checkpointable form (ONE place:
        the segment-boundary and post-loop publications must stay
        identical)."""
        return {"cov_type": ct, "means_c": np.asarray(means_out),
                "cov": np.asarray(cov_out),
                "log_w": np.asarray(log_w_out), "prev_ll": prev}

    def _ingest_device_tables(self, means_out, cov_out, log_w_out,
                              shift) -> None:
        """Host-side publication of the device loop's raw tables into
        the sklearn-frame fitted attributes (shift added back in
        float64; spherical collapses its broadcast variance)."""
        ct = self.covariance_type
        k = self.n_components
        self.means_ = np.asarray(means_out, np.float64)[:k] + shift
        cv_out = np.asarray(cov_out, np.float64)
        if ct == "spherical":
            # The loop carries the scalar variance broadcast over D;
            # collapse back to the sklearn (k,) shape.
            self.covariances_ = cv_out[:k, 0]
        elif ct == "tied":
            self.covariances_ = cv_out               # shared (D, D)
        else:
            self.covariances_ = cv_out[:k]
        w = np.exp(np.asarray(log_w_out, np.float64)[:k])
        self.weights_ = w / w.sum()

    def _fit_on_device(self, ds, mesh, base_iter: int = 0,
                       resume: bool = False, checkpoint_every: int = 0,
                       checkpoint_path=None) -> None:
        """All EM iterations in ONE dispatch (``host_loop=False``) — the
        mixture analogue of ``KMeans._fit_on_device``.  All four
        covariance types: diag/spherical via ``make_gmm_fit_fn``,
        full/tied via their own loops (batched on-device Cholesky per
        iteration; a component collapsing to non-PD surfaces as the
        loud non-finite-loglik error — the float64 host loop gives the
        pointed ill-defined-covariance message instead).  ``base_iter``
        offsets ``n_iter_`` for resumed fits.

        ``checkpoint_every=N`` segments the dispatch (ISSUE 4): the
        convergence baseline ``prev0`` rides each segment as a traced
        argument (the exact acc-dtype value the in-loop carry held at
        the boundary), the raw centered-frame tables hand off between
        segments without any host cast, and the SAME raw tables land in
        the rotating checkpoint (``_dev_tables``) — so segmented ==
        single-dispatch bit-exactly, and kill+resume restores the raw
        carry instead of round-tripping through the float64 shift-added
        attributes (which would not be bit-exact).  A resume WITHOUT
        raw tables (host-loop or pre-ISSUE-4 checkpoint) reconstructs
        from the fitted attributes and seeds ``prev0`` with
        ``lower_bound_``."""
        ct = self.covariance_type
        builder = {"diag": make_gmm_fit_fn, "spherical": make_gmm_fit_fn,
                   "tied": make_gmm_fit_tied_fn,
                   "full": make_gmm_fit_full_fn}[ct]
        # Hashable kwargs form, so the dispatch key below can carry the
        # builder's full static config (cache-key completeness).
        kw_items = tuple(sorted(
            ({"cov_type": ct} if ct in ("diag", "spherical")
             else {}).items()))
        chunk = self._eff_chunk(ds)
        pipeline = self._note_estep_path()
        k = self.n_components
        k_pad = self._k_pad
        d = self.means_.shape[1]
        shift = self._shift()
        acc = np.promote_types(self.dtype, np.float32)

        raw = self._dev_tables if resume else None
        if raw is not None and raw["cov_type"] == ct and \
                raw["means_c"].ndim == 2 and \
                raw["means_c"].shape[0] >= k and \
                raw["means_c"].shape[1] == d:
            # Re-pad the CANONICAL carry for THIS mesh's model-axis
            # layout (ISSUE 5 — the checkpoint may come from any
            # topology).  Padding components are exactly the inert
            # constants the loop carries for them (zero means,
            # unit/identity covariance, -inf log-weight: they never
            # receive responsibility and the loop re-asserts them every
            # iteration), so the REAL components' trajectory is
            # bit-identical whatever k_pad the writer used.  In-memory
            # carries from a fit on this same mesh arrive already
            # padded (shape[0] == k_pad >= k) — trimming to k first
            # makes both sources take the one code path.
            raw_mc = np.asarray(raw["means_c"])[:k]
            raw_cov = np.asarray(raw["cov"])
            raw_lw = np.asarray(raw["log_w"])[:k]
            mc = np.zeros((k_pad, d), raw_mc.dtype)
            mc[:k] = raw_mc
            if ct in ("diag", "spherical"):
                cov0 = np.ones((k_pad, d), raw_cov.dtype)
                cov0[:k] = raw_cov[:k]
            elif ct == "full":
                cov0 = np.broadcast_to(
                    np.eye(d, dtype=raw_cov.dtype),
                    (k_pad, d, d)).copy()
                cov0[:k] = raw_cov[:k]
            else:                               # tied: shared (D, D)
                cov0 = raw_cov
            log_w0 = np.full((k_pad,), -np.inf, raw_lw.dtype)
            log_w0[:k] = raw_lw
            prev = float(raw["prev_ll"])
        else:
            log_w0 = np.full((k_pad,), -np.inf, self.dtype)
            log_w0[:k] = np.log(np.maximum(self.weights_, 1e-300))
            if ct in ("diag", "spherical"):
                cv = np.maximum(
                    self._diag_view(),
                    max(self.reg_covar, float(np.finfo(self.dtype).tiny)))
                # The device loop carries FULL replicated tables (each
                # shard slices its block per iteration, like KMeans'
                # make_fit_fn).
                mc, cov0, _ = self._pad_tables(
                    (self.means_ - shift).astype(self.dtype),
                    cv.astype(self.dtype), log_w0[:k])
            elif ct == "full":
                mc = np.zeros((k_pad, d), self.dtype)
                mc[:k] = (self.means_ - shift).astype(self.dtype)
                cov0 = np.broadcast_to(np.eye(d, dtype=self.dtype),
                                       (k_pad, d, d)).copy()
                cov0[:k] = np.asarray(self.covariances_, self.dtype)
            else:                                     # tied
                mc = np.zeros((k_pad, d), self.dtype)
                mc[:k] = (self.means_ - shift).astype(self.dtype)
                cov0 = np.asarray(self.covariances_, self.dtype)
            prev = float(self.lower_bound_) if resume else -np.inf

        self.checkpoint_segments_ = 0 if checkpoint_every else None
        self.effective_chunk_ = chunk
        shift_dev = jnp.asarray(shift.astype(self.dtype))
        tables = (jnp.asarray(mc), jnp.asarray(cov0), jnp.asarray(log_w0))
        hist_parts = []
        it_done = 0
        seg_idx = 0
        converged = False
        while True:
            seg = (min(checkpoint_every, self.max_iter - it_done)
                   if checkpoint_every else self.max_iter - it_done)

            # Chunk is a dispatch parameter: a RESOURCE_EXHAUSTED from
            # the segment halves it, rebuilds the kernel, and replays
            # from this boundary (== the last checkpoint, ISSUE 5).
            def dispatch(c, _seg=seg, _tables=tables, _prev=prev):
                key = (mesh, c, k, _seg, float(self.tol),
                       float(self.reg_covar), ct, pipeline, builder,
                       kw_items, "gmmfit")
                fit_fn = _STEP_CACHE.get_or_create(key, lambda: builder(
                    mesh, chunk_size=c, k_real=k, max_iter=_seg,
                    tol=float(self.tol), reg_covar=float(self.reg_covar),
                    pipeline=pipeline, **dict(kw_items)))
                return fit_fn(ds.points, ds.weights, shift_dev,
                              *_tables, np.asarray(_prev, acc))

            (means_out, cov_out, log_w_out, it, hist, conv), chunk = \
                self._dispatch_oom_safe(dispatch, chunk, seg_idx)
            seg_idx += 1
            n = int(it)
            hist_np = np.asarray(hist, np.float64)[:n]
            if n and not np.all(np.isfinite(hist_np)):
                # The in-loop finite-ll flag stopped the dispatch at the
                # diverging iteration; roll back to the last-good
                # checkpoint and name it (ISSUE 5).
                self._raise_divergence("log-likelihood",
                                       base_iter + it_done + n)
            hist_parts.append(hist_np)
            it_done += n
            converged = bool(conv)
            if n:
                # The NEXT segment's baseline must be the exact
                # acc-dtype value the in-loop carry held — read it from
                # the returned history, not the float64 attrs.
                prev = float(np.asarray(hist)[n - 1])
            if not checkpoint_every:
                break
            self.checkpoint_segments_ += 1
            self._ingest_device_tables(means_out, cov_out, log_w_out,
                                       shift)
            self.converged_ = converged
            self.n_iter_ = base_iter + it_done
            if it_done:
                self.lower_bound_ = float(hist_parts[-1][-1]) \
                    if len(hist_parts[-1]) else self.lower_bound_
            self._dev_tables = self._pack_dev_tables(
                ct, means_out, cov_out, log_w_out, prev)
            self._write_autockpt(checkpoint_path, base_iter + it_done)
            if converged or it_done >= self.max_iter:
                break
            tables = (means_out, cov_out, log_w_out)   # no host cast

        hist_all = (np.concatenate(hist_parts) if hist_parts
                    else np.zeros(0))
        n_total = it_done
        self._ingest_device_tables(means_out, cov_out, log_w_out, shift)
        self._dev_tables = self._pack_dev_tables(
            ct, means_out, cov_out, log_w_out, prev)
        self.converged_ = converged
        self.n_iter_ = base_iter + n_total
        self.lower_bound_ = float(hist_all[-1]) if n_total else -np.inf
        if self.verbose:
            print(f"EM device loop: {n_total} iterations, "
                  f"mean log-likelihood = "
                  f"{self.lower_bound_:.6f}", flush=True)

    # ------------------------------------------------------------ inference

    def _check_fitted(self):
        if self.means_ is None:
            raise ValueError("Model must be fitted before prediction")

    def _posterior(self, X):
        self._check_fitted()
        ds = self._dataset(X)
        mesh = self._resolve_mesh()
        # Pass the RESOLVED pipeline: the predict builder itself is
        # schedule-independent, but sharing the fit's cache key avoids
        # a duplicate entry whose step fn carries a schedule the fit
        # didn't run (review r8).
        _, predict_fn = _get_fns(mesh, self._eff_chunk(ds),
                                 self.covariance_type,
                                 self._resolve_pipeline())
        labels, logr, lse = predict_fn(ds.points, *self._params_dev(mesh))
        k = self.n_components
        return (np.asarray(labels)[: ds.n],
                np.asarray(logr)[: ds.n, :k].astype(np.float64),
                np.asarray(lse)[: ds.n].astype(np.float64))

    def predict(self, X) -> np.ndarray:
        return self._posterior(X)[0]

    def fitted_state(self) -> dict:
        """Serving handle (ISSUE 6): the read-only description the
        serving engine needs to hold this mixture resident.  GMMs are
        NOT stackable on a batched model axis (per-component covariance
        structure has no shared packed-table form) — mixed-model
        routing dispatches them per model."""
        self._check_fitted()
        return {
            "family": "gmm",
            "model_class": type(self).__name__,
            "k": int(self.n_components),
            "d": int(self.means_.shape[1]),
            "dtype": np.dtype(self.dtype).str,
            "stackable": False,
            "normalize_inputs": False,
            "ops": ("predict", "predict_proba", "score_samples"),
        }

    def quality_profile(self, X=None) -> Optional[dict]:
        """Fit-time serving-quality reference profile (ISSUE 14), the
        mixture-family analogue of ``KMeans.quality_profile``: the
        assignment histogram is the fitted mixing weights (the
        responsibility mass each component holds over the training
        data — what a hard-label serving histogram approximates), and
        the score reference is the per-row NEGATIVE log-likelihood
        (``-lower_bound_``; the ratio detector deactivates itself when
        the reference is non-positive, i.e. when the density exceeds 1
        nat — documented in ``obs.drift``).  With ``X``, both are
        recomputed against that data (one posterior pass)."""
        from kmeans_tpu.obs import drift as obs_drift
        if X is not None:
            self._check_fitted()
            labels, _, lse = self._posterior(X)
            return obs_drift.build_profile(
                family="gmm", model_class=type(self).__name__,
                k=self.n_components,
                counts=np.bincount(np.asarray(labels),
                                   minlength=self.n_components),
                score_kind="neg_log_lik",
                score_per_row=float(-np.mean(lse)),
                n_rows=float(np.asarray(labels).size))
        if self.weights_ is not None:
            return obs_drift.build_profile(
                family="gmm", model_class=type(self).__name__,
                k=self.n_components, counts=self.weights_,
                score_kind="neg_log_lik",
                score_per_row=(float(-self.lower_bound_)
                               if np.isfinite(self.lower_bound_)
                               else None))
        return self._quality_profile

    def fit_predict(self, X, y=None, *, sample_weight=None) -> np.ndarray:
        """Fit and return component labels for X (sklearn convention:
        ``y`` is ignored).  X is placed on device ONCE and shared by the
        fit and the labeling pass."""
        ds = self._dataset(X, sample_weight)
        return self.fit(ds).predict(ds)

    def predict_proba(self, X) -> np.ndarray:
        return np.exp(self._posterior(X)[1])

    def predict_stream(self, make_blocks, *, prefetch: int = 2):
        """Component labels for a bigger-than-memory dataset, one block
        at a time — the inference complement of ``fit_stream`` (mirrors
        ``KMeans.predict_stream``, including its ``prefetch`` staging
        knob).  Yields one int32 (m,) array per block of
        ``make_blocks()``."""
        self._check_fitted()
        return (lab for lab, _, _ in
                self._posterior_stream(make_blocks, prefetch=prefetch))

    def score_samples_stream(self, make_blocks, *, prefetch: int = 2):
        """Per-sample log-likelihood log p(x), one block at a time."""
        self._check_fitted()
        return (lse for _, _, lse in
                self._posterior_stream(make_blocks, prefetch=prefetch))

    def _posterior_stream(self, make_blocks, prefetch: int = 0):
        from kmeans_tpu.data.prefetch import prefetch_iter
        from kmeans_tpu.parallel.sharding import shard_points
        mesh = self._resolve_mesh()
        data_shards, _ = mesh_shape(mesh)
        d = self.means_.shape[1]
        k = self.n_components
        from kmeans_tpu.models.init import _block_of
        params = None

        def stage(item):
            # Producer-side decode + device placement (prefetch > 0):
            # block i+1 stages while block i's E-pass computes.
            block = _block_of(item)          # weights irrelevant here
            block = np.ascontiguousarray(np.asarray(block,
                                                    dtype=self.dtype))
            if block.ndim != 2 or block.shape[1] != d:
                raise ValueError(f"block shape {block.shape} != (*, {d})")
            chunk = self.chunk_size or choose_chunk_size(
                -(-block.shape[0] // data_shards), k, d,
                budget_elems=EM_CHUNK_BUDGET)
            pts, _ = shard_points(block, mesh, chunk)
            return block.shape[0], chunk, pts

        with contextlib.closing(prefetch_iter(make_blocks(), prefetch,
                                              stage)) as it:
            for m, chunk, pts in it:
                _, predict_fn = _get_fns(mesh, chunk,
                                         self.covariance_type,
                                         self._resolve_pipeline())
                if params is None:
                    params = self._params_dev(mesh)
                labels, logr, lse = predict_fn(pts, *params)
                yield (np.asarray(labels)[:m],
                       np.asarray(logr)[:m, :k].astype(np.float64),
                       np.asarray(lse)[:m].astype(np.float64))

    def score_samples(self, X) -> np.ndarray:
        """Per-sample log-likelihood log p(x) under the mixture."""
        return self._posterior(X)[2]

    def score(self, X, y=None) -> float:
        """Mean per-sample log-likelihood (sklearn convention)."""
        return float(np.mean(self.score_samples(X)))

    def sample(self, n_samples: int = 1):
        """Draw (X, component_labels) from the fitted mixture."""
        self._check_fitted()
        rng = np.random.default_rng(self.seed)
        comp = rng.choice(self.n_components, size=n_samples,
                          p=self.weights_ / self.weights_.sum())
        d = self.means_.shape[1]
        z = rng.standard_normal((n_samples, d))
        ct = self.covariance_type
        if ct in ("diag", "spherical"):
            X = self.means_[comp] + z * np.sqrt(self._diag_view()[comp])
        else:
            # x = mu + L z with Sigma = L L^T.
            L = np.linalg.cholesky(np.asarray(self.covariances_,
                                              np.float64))
            X = self.means_[comp] + (
                np.einsum("nde,ne->nd", L[comp], z) if ct == "full"
                else z @ L.T)
        return X.astype(self.dtype), comp.astype(np.int32)

    # ----------------------------------------------------- model selection

    @property
    def precisions_cholesky_(self) -> np.ndarray:
        """sklearn's precision-Cholesky parameterization (P with
        Sigma^-1 = P P^T for 'tied'/'full'; 1/sqrt(var) for
        'diag'/'spherical')."""
        self._check_fitted()
        if self.covariance_type in ("diag", "spherical"):
            return 1.0 / np.sqrt(self.covariances_)
        return self._prec_chol(np.asarray(self.covariances_,
                                          np.float64))[0]

    @property
    def precisions_(self) -> np.ndarray:
        self._check_fitted()
        if self.covariance_type in ("diag", "spherical"):
            return 1.0 / self.covariances_
        p = self.precisions_cholesky_
        return p @ np.swapaxes(p, -1, -2)

    @staticmethod
    def _n_parameters_for(k: int, d: int, cov_type: str) -> int:
        """Free parameters per covariance type (sklearn's count — the
        BIC/AIC penalty), shape-parameterized so the k sweep can score
        every member without a fitted instance per k."""
        cov_params = {"diag": k * d, "spherical": k,
                      "tied": d * (d + 1) // 2,
                      "full": k * d * (d + 1) // 2}[cov_type]
        return (k - 1) + k * d + cov_params

    def _n_parameters(self) -> int:
        return self._n_parameters_for(self.n_components,
                                      self.means_.shape[1],
                                      self.covariance_type)

    def bic(self, X) -> float:
        n = np.asarray(X).shape[0] if not isinstance(X, ShardedDataset) \
            else X.n
        return (-2.0 * self.score(X) * n
                + self._n_parameters() * math.log(n))

    def aic(self, X) -> float:
        n = np.asarray(X).shape[0] if not isinstance(X, ShardedDataset) \
            else X.n
        return -2.0 * self.score(X) * n + 2.0 * self._n_parameters()

    # ------------------------------------------------- checkpoint / pickle

    def _state_dict(self) -> dict:
        """Serializable state (shared by ``save`` and the rotating
        auto-checkpoint writer)."""
        state = {
            "model_class": type(self).__name__,
            "n_components": self.n_components,
            "covariance_type": self.covariance_type,
            "tol": self.tol, "reg_covar": self.reg_covar,
            "max_iter": self.max_iter, "n_init": self.n_init,
            "init_params": self.init_params, "seed": self.seed,
            "model_shards": self.model_shards,
            "chunk_size": self.chunk_size, "host_loop": self.host_loop,
            "pipeline": self.pipeline, "bucket": self.bucket,
            "overlap": self.overlap, "ingest": self.ingest,
            "verbose": self.verbose, "dtype": str(self.dtype),
            "weights_": np.asarray(self.weights_)
            if self.weights_ is not None else np.zeros((0,)),
            "means_": np.asarray(self.means_)
            if self.means_ is not None else np.zeros((0, 0)),
            "covariances_": np.asarray(self.covariances_)
            if self.covariances_ is not None else np.zeros((0, 0)),
            "shift_": np.asarray(self._shift())
            if self.means_ is not None else np.zeros((0,)),
            "converged_": bool(self.converged_),
            "n_iter_": int(self.n_iter_),
            "lower_bound_": float(self.lower_bound_),
            # Restart metadata (n_init > 1): save/load must not silently
            # drop fitted attributes (r3 ADVICE).
            "best_restart_": int(getattr(self, "best_restart_", 0)),
            "restart_lower_bounds_":
                np.asarray(self.restart_lower_bounds_)
                if getattr(self, "restart_lower_bounds_", None) is not None
                else np.zeros((0,)),
        }
        # Topology metadata block (ISSUE 5): informational — the state
        # below is canonical/unsharded, so resume works on any mesh.
        state.update(self._ckpt_meta())
        # Serving-quality reference profile (ISSUE 14, JSON meta block).
        state["quality_profile"] = self.quality_profile()
        # Explicit init arrays are CONFIG, not fitted state: a loaded
        # model that is re-fit must seed exactly like the original.
        for name in ("weights_init", "means_init", "precisions_init"):
            val = getattr(self, name)
            if val is not None:
                state[f"cfg_{name}"] = np.asarray(val)
        # Raw device-loop tables (see __init__): what makes a device-
        # loop resume bit-exact — the centered-frame acc-dtype carry
        # plus the in-dispatch convergence baseline.  Stored CANONICAL
        # (trimmed to the real k — ISSUE 5): the in-memory carry is
        # padded to THIS mesh's model-axis multiple, but padding
        # components are exactly the constants the loop start
        # constructs (zero means, unit/identity covariance, -inf
        # log-weight — they are inert and re-derivable), so trimming
        # here and re-padding at resume for WHATEVER TP layout the
        # resuming model has reproduces the carry bit-for-bit.
        raw = self._dev_tables
        if raw is not None:
            k = self.n_components
            cov = np.asarray(raw["cov"])
            state["dev_means_c"] = np.asarray(raw["means_c"])[:k]
            # tied carries one SHARED (D, D) covariance — no component
            # axis to trim; diag/spherical (k_pad, D) and full
            # (k_pad, D, D) trim to the real k.
            state["dev_cov"] = cov if raw["cov_type"] == "tied" \
                else cov[:k]
            state["dev_log_w"] = np.asarray(raw["log_w"])[:k]
            state["dev_prev_ll"] = float(raw["prev_ll"])
            state["dev_cov_type"] = raw["cov_type"]
        return state

    def save(self, path) -> None:
        """Checkpoint fitted state AND explicit init arrays (mirrors
        ``KMeans.save`` — the reference has no serialization at all,
        SURVEY.md §5).  Multi-host: call on EVERY process; the shared
        primary-gated writer (``checkpoint.save_state_primary``) handles
        the single-writer + barrier contract."""
        from kmeans_tpu.utils import checkpoint as ckpt
        ckpt.save_state_primary(path, self._state_dict(),
                                "kmeans_tpu.gmm.save")

    def _restore_state(self, state: dict) -> None:
        """Restore fitted attributes from a ``_state_dict`` payload
        (shared by ``load`` and path-``resume``)."""
        if state["means_"].size:
            self.weights_ = np.asarray(state["weights_"], np.float64)
            self.means_ = np.asarray(state["means_"], np.float64)
            self.covariances_ = np.asarray(state["covariances_"],
                                           np.float64)
            self.shift_ = np.asarray(state["shift_"], np.float64)
            self.converged_ = bool(state["converged_"])
            self.n_iter_ = int(state["n_iter_"])
            self.lower_bound_ = float(state["lower_bound_"])
            self.best_restart_ = int(state.get("best_restart_", 0))
            rlb = state.get("restart_lower_bounds_")
            self.restart_lower_bounds_ = (
                np.asarray(rlb, np.float64)
                if rlb is not None and rlb.size else None)
        # Pre-r18 checkpoints carry no profile -> None.
        self._quality_profile = state.get("quality_profile")
        # Clear-then-restore: a stale in-memory carry from an earlier
        # fit must never shadow the checkpoint.
        self._dev_tables = None
        if "dev_means_c" in state:
            ct = str(state.get("dev_cov_type", self.covariance_type))
            k = self.n_components
            cov = np.asarray(state["dev_cov"])
            # Canonicalize on the way in (ISSUE 5): r9 checkpoints
            # stored the tables PADDED to the writer's model-axis
            # multiple; trimming to the real k makes every checkpoint
            # topology-portable — ``_fit_on_device`` re-pads for the
            # RESUMING mesh's layout (padding components are the inert
            # loop constants, so this is bit-exact).
            self._dev_tables = {
                "cov_type": ct,
                "means_c": np.asarray(state["dev_means_c"])[:k],
                "cov": cov if ct == "tied" else cov[:k],
                "log_w": np.asarray(state["dev_log_w"])[:k],
                "prev_ll": float(state["dev_prev_ll"]),
            }

    @classmethod
    def load(cls, path) -> "GaussianMixture":
        from kmeans_tpu.utils import checkpoint as ckpt
        state = ckpt.load_state(path)
        inits = {name: state[f"cfg_{name}"]
                 for name in ("weights_init", "means_init",
                              "precisions_init")
                 if f"cfg_{name}" in state}
        pipe_raw = state.get("pipeline", "auto")
        pipeline = "auto" if str(pipe_raw) == "auto" else int(pipe_raw)
        model = cls(n_components=int(state["n_components"]),
                    covariance_type=str(state["covariance_type"]),
                    tol=float(state["tol"]),
                    reg_covar=float(state["reg_covar"]),
                    max_iter=int(state["max_iter"]),
                    n_init=int(state.get("n_init", 1)),
                    init_params=str(state["init_params"]),
                    seed=int(state["seed"]),
                    model_shards=int(state.get("model_shards", 1)),
                    chunk_size=(int(state["chunk_size"])
                                if state["chunk_size"] is not None else
                                None),
                    host_loop=bool(state.get("host_loop", True)),
                    pipeline=pipeline,
                    # Pre-r19 checkpoints carry no bucket -> exact shape.
                    bucket=(lambda b: b if isinstance(b, str)
                            else int(b))(state.get("bucket", 0)),
                    # Pre-r22 checkpoints carry neither knob -> the
                    # per-run platform/committed-rule resolutions.
                    overlap=(lambda o: o if isinstance(o, str)
                             else int(o))(state.get("overlap", "auto")),
                    ingest=str(state.get("ingest", "auto")),
                    verbose=bool(state["verbose"]),
                    dtype=np.dtype(str(state["dtype"])), **inits)
        model._restore_state(state)
        return model

    def __getstate__(self) -> dict:
        """CROSS-PROCESS pickle support: the ``jax.sharding.Mesh`` of
        Device handles is dropped (KMeans does the same); an unpickled
        model lazily rebuilds a mesh on next use."""
        state = dict(self.__dict__)
        state["mesh"] = None
        state["_params_cache"] = None     # device arrays don't pickle
        return state

    def __deepcopy__(self, memo):
        """In-process deepcopy keeps the (copyable, user-configured)
        mesh — only cross-process pickling must drop device handles
        (same contract as ``KMeans.__deepcopy__``)."""
        import copy as _copy
        new = self.__class__.__new__(self.__class__)
        memo[id(self)] = new
        for name, value in self.__dict__.items():
            if name in ("mesh", "_params_cache"):
                new.__dict__[name] = value     # share device handles
            else:
                new.__dict__[name] = _copy.deepcopy(value, memo)
        return new

    def get_params(self, deep: bool = True) -> dict:
        return {name: getattr(self, name) for name in self._PARAM_NAMES}

    def set_params(self, **params) -> "GaussianMixture":
        """Route new values through ``__init__`` so they get exactly the
        constructor's validation and canonicalization (r2 ADVICE: raw
        attribute assignment accepted dtype strings, n_components=0,
        covariance_type='full' silently), then restore fitted state."""
        for name in params:
            if name not in self._PARAM_NAMES:
                raise ValueError(f"invalid parameter {name!r} for "
                                 f"GaussianMixture")
        merged = self.get_params()
        merged.update(params)
        saved = dict(self.__dict__)
        try:
            self.__init__(**merged)
        except Exception:
            self.__dict__.clear()
            self.__dict__.update(saved)
            raise
        for name, value in saved.items():
            if name not in self._PARAM_NAMES:
                self.__dict__[name] = value
        return self
