"""Mini-batch K-Means (beyond-reference superset).

The reference has only full-batch Lloyd iterations (kmeans_spark.py:266-313).
This variant (Sculley 2010-style) reuses the same fused SPMD step on a seeded
per-iteration sample and applies per-center count-weighted incremental
updates — useful when N is far larger than one pass per iteration justifies.
Shares every guard and logging behavior with :class:`KMeans`.

Two sampling engines (``sampling=`` constructor arg):

* ``'device'`` (default) — the dataset is uploaded ONCE (or passed as an
  already-resident :class:`ShardedDataset`, host copy not required) and each
  iteration draws its batch on device via seeded Gumbel top-k inside the
  same dispatch that computes the batch statistics
  (``parallel.distributed.make_minibatch_step_fn``).  No per-iteration
  host->device traffic at all — the r1 host path was dispatch/transfer-bound
  on tunneled chips (r1 VERDICT #4).
* ``'host'`` — the r1 behavior: per-iteration host ``rng.choice`` + batch
  upload.  Still the right engine when X is larger than device memory
  (only one batch is ever resident).

``host_loop=False`` additionally runs ALL iterations in one dispatch
(``make_minibatch_fit_fn`` — the mini-batch analogue of the flagship
``make_fit_fn`` loop).  Measured on a tunneled v5e at N=2M, D=128, k=1024,
batch 65536: 3.1 ms/iter on-device loop vs 105 ms/iter per-iteration
dispatches vs ~1.8 s/iter for the r1 host-upload path.

Both engines derive iteration i's randomness purely from ``(seed, i)``, so
checkpoint/resume continues the exact batch sequence; their RNG streams
differ, so trajectories are not comparable ACROSS engines (each is
bit-deterministic within itself).

Dead-center recovery (``reassignment_ratio``, default 0.01 like sklearn):
a center whose lifetime count falls below ``reassignment_ratio *
seen.max()`` is re-seeded from rows of the current batch every
``10 * k`` processed samples — the Sculley-update gate (``counts > 0``)
would otherwise freeze a dead center FOREVER (r3 VERDICT weak #1).  This
is the mini-batch analogue of the reference's one fault path: its
empty-cluster resample (kmeans_spark.py:190-204) also re-draws
replacement centers from the data.  Both device engines draw candidate
rows with the same seeded Gumbel-top-k schedule
(``parallel.distributed._batch_candidates``), so per-iteration and
one-dispatch trajectories agree; the host engine draws from its own host
batch stream.  ``reassignment_ratio=0`` disables recovery (the r3
behavior).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from kmeans_tpu.models.kmeans import KMeans, _STEP_CACHE
from kmeans_tpu.parallel.multihost import fleet_barrier
from kmeans_tpu.models.init import resolve_init
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs import note_progress as obs_note_progress
from kmeans_tpu.utils.logging import IterationLogger

_SAMPLING = ("device", "host")


class MiniBatchKMeans(KMeans):
    _PARAM_NAMES = KMeans._PARAM_NAMES + ("batch_size", "sampling",
                                          "reassignment_ratio")
    # The inherited k-sweep engine batches full-batch Lloyd members; the
    # Sculley update loop is a different engine — opt out (ISSUE 7).
    _sweepable = False

    def __init__(self, k: int = 3, max_iter: int = 100,
                 tolerance: float = 1e-4, seed: int = 42,
                 compute_sse: bool = False, *, batch_size: int = 4096,
                 sampling: str = "device",
                 reassignment_ratio: float = 0.01, **kwargs):
        super().__init__(k, max_iter, tolerance, seed, compute_sse, **kwargs)
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        if sampling not in _SAMPLING:
            raise ValueError(f"sampling must be one of {_SAMPLING}, "
                             f"got {sampling!r}")
        if reassignment_ratio < 0:
            raise ValueError(f"reassignment_ratio must be >= 0, got "
                             f"{reassignment_ratio}")
        self.batch_size = batch_size
        self.sampling = sampling
        self.reassignment_ratio = float(reassignment_ratio)
        # Set unconditionally (like KMeans' best_restart_): resume- and
        # partial_fit-trained models must not raise on these reads.
        self.init_inertias_ = None
        self.best_init_ = 0
        # Total dataset weight of the last fit() (ISSUE 14): the
        # quality-profile score-per-row denominator — ``inertia_`` is
        # the TOTAL-WEIGHT-scaled SSE estimate while ``cluster_sizes_``
        # holds only the last batch's counts, so neither fitted attr
        # can stand in for it.  None under partial_fit (batch-scale
        # inertia there divides by the batch counts).
        self._profile_total_w = None

    def _auto_n_init(self) -> int:
        """sklearn resolves MiniBatchKMeans ``n_init='auto'`` to 3 (not
        KMeans' 10): candidates are only SCORED on one pass, not trained,
        so fewer random draws give the intended cost/quality trade."""
        return 3

    def _reassign_every(self, batch_global: int) -> int:
        """Reassignment cadence: the first iteration count n with
        ``n * batch > 10 * k`` — sklearn's ``_random_reassign`` rule is
        the STRICT inequality ``10 * k < n_since_last_reassign``, so
        ``batch == 10 * k`` gives a period of 2, which floor-div + 1
        reproduces exactly.  Deterministic in the absolute iteration
        index, so resumes keep the cadence."""
        return 10 * self.k // max(batch_global, 1) + 1

    # ------------------------------------------------------------------- fit

    def fit(self, X, y=None, *, sample_weight=None, resume=False,
            checkpoint_every: int = 0,
            checkpoint_path=None) -> "MiniBatchKMeans":
        """Fit with mini-batch Sculley updates.  ``sample_weight``
        follows sklearn's MiniBatch semantics: rows are SAMPLED
        uniformly and the weights scale every batch statistic (sums,
        counts, lifetime ``seen``) — exactly what sklearn's
        ``MiniBatchKMeans.fit(X, sample_weight=...)`` does.

        ``resume`` may be a checkpoint path (``.prev`` corrupt fallback
        included), and ``checkpoint_every=N`` auto-checkpoints every N
        iterations with the rotating atomic writer — the one-dispatch
        device loop becomes segmented exactly like ``KMeans.fit``'s
        (both engines key iteration i's randomness off the ABSOLUTE
        ``(seed, i)``, so boundaries never re-draw and resume is
        bit-exact)."""
        checkpoint_every = self._check_ckpt(checkpoint_every,
                                            checkpoint_path)
        resume = self._resolve_resume(resume)
        if self.sampling == "host":
            # The host engine exists for X bigger than device memory:
            # weights stay on the host (routing through cache() would
            # upload the whole dataset, review r4).
            return self._fit_host(X, sample_weight=sample_weight,
                                  resume=resume,
                                  checkpoint_every=checkpoint_every,
                                  checkpoint_path=checkpoint_path)
        X = self._apply_sample_weight(X, sample_weight)
        self._fit_device(X, resume=resume,
                         checkpoint_every=checkpoint_every,
                         checkpoint_path=checkpoint_path)
        # Multi-host process-local fits materialize labels_ HERE, while
        # every process is still executing fit: deferring the global
        # assignment dispatch to a later labels_ read or pickle on ONE
        # process (e.g. an is_primary() checkpoint block) would run an
        # SPMD computation the other processes never join (review r4).
        # Single-host fits keep the documented lazy labels_.
        from kmeans_tpu.parallel.sharding import ShardedDataset
        if self.compute_labels and \
                isinstance(self._fit_ds, ShardedDataset) and \
                not self._fit_ds.points.is_fully_addressable:
            _ = self.labels_
        return self

    def _resume_or_init(self, init_src, resume: bool):
        """Shared fit prelude: (centroids float64, start_iter, seen).

        Resume prefers the ``_centroids_f64`` carry over the public
        ``centroids`` attr: the per-iteration Sculley engines interpolate
        in float64 and only CAST to the model dtype for publication, so
        resuming from the cast copy would lose the carry's low bits and
        break bit-exact kill/resume parity for float32 models (ISSUE 4;
        the one-dispatch device loop carries the compute dtype, for
        which the f64 round trip is exact either way)."""
        if resume and self.centroids is not None:
            carried = getattr(self, "_centroids_f64", None)
            cents = (np.asarray(carried, dtype=np.float64)
                     if carried is not None
                     else np.asarray(self.centroids, dtype=np.float64))
            return (cents, self.iterations_run,
                    np.asarray(self._seen, dtype=np.float64))
        centroids = self._select_init(init_src).astype(np.float64)
        self.sse_history = []
        self.iterations_run = 0
        return centroids, 0, np.zeros(self.k)

    def _select_init(self, init_src) -> np.ndarray:
        """sklearn-style ``n_init``: draw candidate inits and keep the
        one scoring the LOWEST inertia, then run ONE training session —
        sklearn's MiniBatchKMeans evaluates candidate inits rather than
        running full restarts (its n_init semantics differ from
        KMeans').  Scoring: exact full-data SSE when the dataset is
        device-resident (one fused dispatch per candidate — cheap
        against the fit), else a seeded 3*batch_size validation subset
        (sklearn's init_size heuristic).  Records ``init_inertias_`` /
        ``best_init_``; one candidate (n_init=1 or an explicit init
        array) skips scoring entirely."""
        from kmeans_tpu.parallel.sharding import ShardedDataset
        seeds = self._restart_seeds()
        cands = [np.asarray(resolve_init(self.init, init_src, self.k, s))
                 for s in seeds]
        if len(cands) == 1:
            self.init_inertias_ = None
            self.best_init_ = 0
            return cands[0]
        if isinstance(init_src, ShardedDataset):
            ds = init_src
            # _prepare keys the step fn on the dataset's OWN chunk.
            _, mesh, model_shards, step_fn, _ = self._prepare(ds)
            def score(c):
                st = step_fn(ds.points, ds.weights, self._put_centroids(
                    c.astype(self.dtype), mesh, model_shards))
                return float(st.sse)
        else:
            from kmeans_tpu.models.init import as_source
            src = as_source(init_src)
            X = np.asarray(src.host)
            hw = src.host_weights             # None when unweighted
            n = X.shape[0]
            take = min(n, max(3 * self.batch_size, 3 * self.k))
            rng = np.random.default_rng([self.seed, 0x1717])
            idx = rng.choice(n, size=take, replace=False)
            val = np.ascontiguousarray(X[idx].astype(self.dtype))
            vw = None if hw is None else np.asarray(hw)[idx]
            from kmeans_tpu.parallel.sharding import shard_points
            mesh, model_shards, step_fn, _, chunk = self._setup(
                take, X.shape[1])
            pts, w = shard_points(val, mesh, chunk, sample_weight=vw)
            def score(c):
                st = step_fn(pts, w, self._put_centroids(
                    c.astype(self.dtype), mesh, model_shards))
                return float(st.sse)
        inertias = [score(c) for c in cands]
        best = int(np.argmin(inertias))
        self.init_inertias_ = np.asarray(inertias, np.float64)
        self.best_init_ = best
        return cands[best]

    def _resolve_host_loop_mb(self, mesh) -> bool:
        """``host_loop='auto'`` for the mini-batch device engine (review
        r5: the inherited default was silently truthy here).  No step is
        timed: per-batch compute is sub-ms by construction (the batch is
        the user-bounded ``batch_size``), so any platform whose dispatch
        RTT exceeds the 5 ms floor is dispatch-bound per the same
        measurement that motivated the device loop (~5 round trips/iter,
        ``_fit_device_loop`` docstring).  The device loop is bit-matched
        to the per-iteration path (tests/test_minibatch_device.py), so
        the switch needs only verbose=False (per-iteration prints) and a
        single process (no cross-process decision divergence)."""
        import jax
        from kmeans_tpu.models.kmeans import _dispatch_rtt, _hint_once
        if self.host_loop is True or self.host_loop is False:
            return self.host_loop
        if jax.process_count() > 1:
            return True
        rtt = _dispatch_rtt(mesh)
        if rtt <= 5e-3:
            return True
        # Host-side Sculley hooks: a subclass overriding the per-batch
        # update must never be silently routed to the device loop (the
        # same guard KMeans._resolve_host_loop applies to Lloyd hooks).
        base_hooks = (
            type(self)._apply_batch_stats
            is MiniBatchKMeans._apply_batch_stats
            and type(self)._incremental_update
            is MiniBatchKMeans._incremental_update)
        if base_hooks and not self.verbose:
            _hint_once(
                "auto_switched_mb",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms dominates "
                f"the sub-ms mini-batch step on this platform — running the "
                f"whole fit as one device dispatch (host_loop=False "
                f"semantics, bit-matched batch sequence; pass "
                f"host_loop=True to force the per-iteration host engine)")
            return False
        if not base_hooks:
            _hint_once(
                "auto_hint_mb_hooks",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms dominates "
                f"the sub-ms mini-batch step on this platform, but "
                f"{type(self).__name__}'s host-side batch hooks require "
                f"the per-iteration engine — that latency is unavoidable "
                f"for this estimator here")
        else:
            _hint_once(
                "auto_hint_mb",
                f"host_loop='auto': dispatch RTT {rtt*1e3:.0f} ms dominates "
                f"the sub-ms mini-batch step on this platform (~5 round "
                f"trips per iteration); set host_loop=False (one-dispatch "
                f"fit) or verbose=False (lets 'auto' switch itself) to "
                f"reclaim it")
        return True

    def _mb_step_getter(self, mesh, bs_local: int, mode: str):
        """The sampling-step cache accessor — ONE key construction
        shared by the fit body and the overlapped prelude's warm
        (duplicating the tuple risks silent divergence)."""
        from kmeans_tpu.parallel import distributed as dist

        def get_step(nc: int):
            return _STEP_CACHE.get_or_create(
                (mesh, bs_local, mode, nc, "mbstep"),
                lambda: dist.make_minibatch_step_fn(
                    mesh, batch_per_shard=bs_local, mode=mode,
                    n_candidates=nc))
        return get_step

    def _staged_dataset(self, X):
        """The mini-batch fit's dataset prelude (ISSUE 18b): with
        ``overlap`` resolved on and a host-array input, the upload runs
        in the prefetch producer thread while THIS thread resolves —
        and, with an AOT store active, loads-or-compiles — the fused
        sampling-step program (the r19 ``utils.aot`` overlap entry
        point, on the mini-batch prelude too).  Bit-exact parity with
        the serial path: only WHERE the prelude runs moves."""
        import jax
        from kmeans_tpu.parallel.sharding import ShardedDataset
        if not self._resolve_overlap() or isinstance(X, ShardedDataset) \
                or jax.process_count() != 1:
            return self._dataset(X)
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2:
            return self._dataset(X)
        from kmeans_tpu.data.prefetch import close_source, prefetch_iter
        it = prefetch_iter([X], 1, stage=self.cache)
        try:
            self._warm_mb(*X.shape)
            ds = next(it)
        finally:
            close_source(it)
        return ds

    def _warm_mb(self, n: int, d: int) -> None:
        """Resolve (and AOT-warm) the per-iteration sampling step for
        the (n, d) fit about to run — the consumer half of the
        overlapped prelude.  Derivations mirror ``_fit_device``'s
        exactly (batch/mode from shapes known before any data moves),
        so the fit-body cache lookups are pure hits.  Only the plain
        step is warmed (the candidate variant and the device-loop
        program dispatch later, off the TTFI path — the KMeans
        warm-only-what-will-run discipline)."""
        import jax
        from jax.sharding import NamedSharding, SingleDeviceSharding
        from jax.sharding import PartitionSpec as P
        from kmeans_tpu.parallel import distributed as dist
        from kmeans_tpu.parallel.mesh import DATA_AXIS, mesh_shape
        mesh = self._resolve_mesh()
        data_shards, model_shards = mesh_shape(mesh)
        bs_local = max(8, -(-min(self.batch_size, n) // data_shards))
        mode = self._mode(bs_local, d)
        step_fn = self._mb_step_getter(mesh, bs_local, mode)(0)
        if not hasattr(step_fn, "warm") or self.host_loop is False:
            return
        chunk = self._chunk_for(n, d)
        mult = data_shards * chunk
        n_pad = -(-max(self._bucket_target(n), n) // mult) * mult
        k_pad = -(-self.k // model_shards) * model_shards
        sds = jax.ShapeDtypeStruct
        step_fn.warm(
            sds((n_pad, d), self.dtype,
                sharding=NamedSharding(mesh, P(DATA_AXIS, None))),
            sds((n_pad,), self.dtype,
                sharding=NamedSharding(mesh, P(DATA_AXIS))),
            sds((k_pad, d), self.dtype,
                sharding=dist.centroid_sharding(mesh)),
            sds((2,), np.uint32,
                sharding=SingleDeviceSharding(jax.devices()[0])),
            sds((), np.int32))

    def _fit_device(self, X, *, resume: bool, checkpoint_every: int = 0,
                    checkpoint_path=None) -> "MiniBatchKMeans":
        """On-device sampling engine: resident dataset, one dispatch per
        iteration (sampling + batch statistics fused)."""
        import jax
        from kmeans_tpu.parallel import distributed as dist
        from kmeans_tpu.parallel.mesh import mesh_shape

        ds = self._staged_dataset(X)           # host copy NOT required
        mesh = self._resolve_mesh()
        data_shards, model_shards = mesh_shape(mesh)
        bs = min(self.batch_size, ds.n)
        # Rounded up: every shard contributes the same (>= 8-row sublane-
        # aligned) count, so the effective batch is bs_local * data_shards.
        bs_local = max(8, -(-bs // data_shards))
        # Fleet prelude (ISSUE 13): minibatch rows/iteration = the
        # effective global batch (sampled, not the dataset size).
        self._progress_rows = bs_local * data_shards
        fleet_barrier("fit-start")
        log = IterationLogger(self.verbose and jax.process_index() == 0)

        self._set_fit_data(ds)                 # feeds lazy labels_
        if not ds.points.is_fully_addressable and not ds.labelable:
            # Layout-less hand-built global arrays cannot unpad labels.
            self._fit_ds, self._labels_cache = None, None
            self._labels_error = (
                "labels_ is not available for this multi-host fit "
                "(unknown per-process layout); call predict on each "
                "process's local rows")
        centroids, start_iter, seen = self._resume_or_init(ds, resume)
        if start_iter == 0:
            self.iter_times_ = []
        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)
        base_key = jax.random.PRNGKey(self.seed)
        # The mini-batch statistics pass is ONE scan chunk
        # (batch_per_shard == chunk), so the pipelined Lloyd schedule
        # DEGENERATES to the serial body whatever the knob says
        # (distributed.make_minibatch_step_fn) — record what actually
        # runs, not what was asked for: 'fused-pallas' when the fused
        # kernel owns the pass (the KMeans._note_estep_path convention),
        # 'serial' otherwise.
        self.estep_path_ = ("fused-pallas"
                            if self._mode(ds.n, ds.d) in dist.PALLAS_MODES
                            else "serial")
        self.bf16_guard_corrected_rows_ = None

        if not self._resolve_host_loop_mb(mesh):
            return self._fit_device_loop(ds, mesh, model_shards, bs_local,
                                         centroids, start_iter, seen,
                                         base_key, log, checkpoint_every,
                                         checkpoint_path)
        self.checkpoint_segments_ = 0 if checkpoint_every else None

        # auto resolves against the BATCH row count — that's what the
        # kernel would process per pass.
        mode = self._mode(bs_local, ds.d)
        n_cand = self.k if self.reassignment_ratio > 0 else 0
        re_every = self._reassign_every(bs_local * data_shards)

        get_step = self._mb_step_getter(mesh, bs_local, mode)
        step_fn = get_step(0)
        # Candidate variant dispatched ONLY on reassignment iterations —
        # the candidate Gumbel stream is keyed independently of the batch
        # stream, so alternating programs is bit-identical to always
        # drawing; off-cadence iterations skip the extra (k, D) transfer.
        step_cand_fn = get_step(n_cand) if n_cand else None
        # Scale factor target: total dataset weight (== n when unweighted).
        total_w = float(np.asarray(
            jax.jit(lambda w: w.sum())(ds.weights)))
        self._profile_total_w = total_w       # quality-profile denominator

        for iteration in range(start_iter, self.max_iter):
            t0 = time.perf_counter()
            do_re = bool(n_cand) and ((iteration + 1) % re_every == 0)
            # Batch i is a pure function of (seed, i): resume continues the
            # exact sequence an uninterrupted run would draw.  The
            # 'dispatch' span covers dispatch + the combined transfer
            # (the device_get is the sync point).
            with obs_trace.span("dispatch", tag="minibatch/step",
                                iteration=iteration):
                out = (step_cand_fn if do_re else step_fn)(
                    ds.points, ds.weights,
                    self._put_centroids(
                        centroids.astype(self.dtype), mesh, model_shards),
                    base_key, np.int32(iteration))
                # One combined transfer (each separate np.asarray pays a
                # full host round trip on tunneled platforms).
                if do_re:
                    stats, cand_rows, cand_valid = out
                    sums_d, counts_d, sse_d, cand_rows, cand_valid = \
                        jax.device_get((stats.sums, stats.counts,
                                        stats.sse, cand_rows, cand_valid))
                else:
                    stats = out
                    sums_d, counts_d, sse_d = jax.device_get(
                        (stats.sums, stats.counts, stats.sse))
                    cand_rows = cand_valid = None
            sums = np.asarray(sums_d, dtype=np.float64)[: self.k]
            counts = np.asarray(counts_d, dtype=np.float64)[: self.k]
            batch_w = float(counts.sum())
            centroids, seen, max_shift = self._apply_batch_stats(
                sums, counts, centroids, seen, iteration, log,
                sse=float(sse_d),
                sse_scale=total_w / max(batch_w, 1.0),
                candidates=cand_rows, cand_valid=cand_valid,
                do_reassign=do_re)
            self.iter_times_.append(time.perf_counter() - t0)
            if checkpoint_every and (iteration + 1) % checkpoint_every == 0:
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, iteration + 1)
            if max_shift < self.tolerance:
                log.converged(iteration + 1)
                break
        if checkpoint_every and self.iterations_run % checkpoint_every:
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.iterations_run)
        return self

    def _fit_device_loop(self, ds, mesh, model_shards, bs_local, centroids,
                         start_iter, seen, base_key, log,
                         checkpoint_every: int = 0,
                         checkpoint_path=None) -> "MiniBatchKMeans":
        """Whole-mini-batch-fit-in-one-dispatch (``host_loop=False``): no
        per-iteration host sync at all — on tunneled chips the per-
        iteration path is dispatch-bound (~5 round trips/iter vs sub-ms
        batch compute).  Same key schedule as the per-iteration path, so
        the two produce the same batch sequence.

        ``checkpoint_every=N`` segments the dispatch exactly like
        ``KMeans._fit_on_device``: the loop keys every batch draw and
        the reassignment cadence off the ABSOLUTE iteration
        (``iter0 + i``), and the carried (centroids, seen) state round-
        trips the boundary through the same dtype casts a resumed fit
        applies — so segmented == single-dispatch bit-exactly (f32/f64)
        and kill+resume == uninterrupted."""
        import jax
        from kmeans_tpu.parallel import distributed as dist

        if self.max_iter - start_iter <= 0:
            return self
        mode = self._mode(bs_local, ds.d)
        from kmeans_tpu.parallel.mesh import mesh_shape
        data_shards, _ = mesh_shape(mesh)
        re_every = self._reassign_every(bs_local * data_shards)
        self.checkpoint_segments_ = 0 if checkpoint_every else None
        base_hist = list(self.sse_history)
        cents_dev = self._put_centroids(centroids.astype(self.dtype), mesh,
                                        model_shards)
        seen_arr = np.asarray(seen, dtype=self.dtype)
        sse_parts, shift_parts = [], []
        it0 = start_iter
        t0 = time.perf_counter()
        while True:
            seg = (min(checkpoint_every, self.max_iter - it0)
                   if checkpoint_every else self.max_iter - it0)
            cache_key = (mesh, bs_local, mode, self.k, seg,
                         float(self.tolerance), self.compute_sse,
                         float(self.reassignment_ratio), re_every, "mbfit")
            fit_fn = _STEP_CACHE.get_or_create(
                cache_key, lambda: dist.make_minibatch_fit_fn(
                    mesh, batch_per_shard=bs_local, mode=mode,
                    k_real=self.k, max_iter=seg,
                    tolerance=float(self.tolerance),
                    history_sse=self.compute_sse,
                    reassignment_ratio=float(self.reassignment_ratio),
                    reassign_every=re_every))
            # One 'segment'/'dispatch' span pair per segment (the
            # mini-batch device loop dispatches directly — it has no
            # OOM-backoff wrapper — so the span pair mirrors
            # AutoCheckpointMixin._dispatch_oom_safe's shape).
            with obs_trace.span("segment", index=len(sse_parts)), \
                    obs_trace.span("dispatch", tag="fit/segment"):
                cents, seen_out, n_iters, sse_hist, shift_hist, counts = \
                    jax.block_until_ready(
                        fit_fn(ds.points, ds.weights, cents_dev, base_key,
                               np.int32(it0), seen_arr))
            n = int(n_iters)
            it0 += n
            sse_parts.append(np.asarray(sse_hist, np.float64)[:n])
            shift_parts.append(np.asarray(shift_hist, np.float64)[:n])
            if not checkpoint_every:
                break
            self.checkpoint_segments_ += 1
            converged = n < seg or (n > 0 and
                                    shift_parts[-1][-1] < self.tolerance)
            cents_host = np.asarray(cents, dtype=self.dtype)
            if not np.all(np.isfinite(cents_host)):  # don't checkpoint NaN
                # Divergence-rollback exit (ISSUE 5): the in-loop
                # all-finite flag stopped the dispatch at the diverging
                # iteration; restore the last-good checkpoint + name it.
                self._raise_divergence("centroids", it0)
            # Boundary state -> valid resume point, then write + hook.
            self.centroids = cents_host
            self._centroids_f64 = np.asarray(cents_host, dtype=np.float64)
            self._seen = np.asarray(seen_out, dtype=np.float64)
            self.cluster_sizes_ = np.asarray(counts, dtype=np.int64)
            self.iterations_run = it0
            if self.compute_sse:
                self.sse_history = base_hist + [
                    float(s) for part in sse_parts for s in part]
            self._write_autockpt(checkpoint_path, it0)
            if converged or it0 >= self.max_iter:
                break
            cents_dev = self._put_centroids(cents_host, mesh, model_shards)
            seen_arr = np.asarray(self._seen, dtype=self.dtype)
        elapsed = time.perf_counter() - t0
        n_total = it0 - start_iter
        self.sse_history = base_hist

        self.centroids = np.asarray(cents, dtype=self.dtype)
        if not np.all(np.isfinite(self.centroids)):
            self._raise_divergence("centroids", start_iter + n_total)
        # The device loop's carry IS the compute dtype — publish its
        # exact f64 image so a later resume (which round-trips through
        # _centroids_f64) continues bit-identically.
        self._centroids_f64 = np.asarray(self.centroids, dtype=np.float64)
        self._seen = np.asarray(seen_out, dtype=np.float64)
        self.cluster_sizes_ = np.asarray(counts, dtype=np.int64)
        self.iterations_run = start_iter + n_total
        self.iter_times_.extend([elapsed / max(n_total, 1)] * n_total)
        sse_hist = (np.concatenate(sse_parts) if sse_parts
                    else np.zeros(0))
        shift_hist = (np.concatenate(shift_parts) if shift_parts
                      else np.zeros(0))
        if self.compute_sse:
            self.sse_history.extend(float(s) for s in sse_hist)
        log.iteration(self.iterations_run - 1,
                      float(shift_hist[-1]) if n_total else 0.0,
                      list(self.cluster_sizes_),
                      self.sse_history[-1] if
                      (self.compute_sse and self.sse_history) else None)
        if n_total and shift_hist[-1] < self.tolerance:
            log.converged(self.iterations_run)
        return self

    def _fit_host(self, X, y=None, *, sample_weight=None,
                  resume: bool = False, checkpoint_every: int = 0,
                  checkpoint_path=None) -> "MiniBatchKMeans":
        """Host sampling engine (the r1 path): per-iteration host
        ``rng.choice`` + batch upload.  Use when X exceeds device
        memory — weights are validated and kept on the host (no full
        upload ever happens)."""
        from kmeans_tpu.parallel.sharding import (ShardedDataset,
                                                  _validate_sample_weight)
        from kmeans_tpu.models.init import as_source
        hw = None
        if isinstance(X, ShardedDataset):
            if X.host is None:
                raise ValueError("sampling='host' needs host data to draw "
                                 "batches; pass a NumPy array or use "
                                 "sampling='device'")
            if sample_weight is not None:
                raise ValueError("pass sample_weight when caching the "
                                 "dataset, not on a pre-built "
                                 "ShardedDataset")
            hw = X.host_weights               # None when unweighted
            X = X.host
        X = np.ascontiguousarray(np.asarray(X, dtype=self.dtype))
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        n, d = X.shape
        if sample_weight is not None:
            hw = _validate_sample_weight(sample_weight, n, self.dtype)
        bs = min(self.batch_size, n)
        total_w = float(hw.sum()) if hw is not None else float(n)
        self._profile_total_w = total_w   # quality-profile denominator
        self._progress_rows = bs          # fleet prelude (ISSUE 13)
        fleet_barrier("fit-start")
        self._set_fit_data(X)                         # feeds lazy labels_
        import jax
        log = IterationLogger(self.verbose and jax.process_index() == 0)

        # The weighted source keeps init draws off zero-weight rows
        # (forgy_init's invariant) and weights the n_init scoring.
        centroids, start_iter, seen = self._resume_or_init(
            as_source(X, hw), resume)
        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)
        self.checkpoint_segments_ = 0 if checkpoint_every else None

        for iteration in range(start_iter, self.max_iter):
            # Per-iteration derived RNG: batch i is a pure function of
            # (seed, i), so a checkpointed run resumes the SAME batch
            # sequence an uninterrupted run would see.  Rows are drawn
            # UNIFORMLY; weights scale the statistics (sklearn's
            # MiniBatch sample_weight semantics).
            rng = np.random.default_rng([self.seed, iteration])
            idx = rng.choice(n, size=bs, replace=False)
            centroids, seen, max_shift = self._incremental_update(
                X[idx], centroids, seen, iteration, log,
                batch_weight=hw[idx] if hw is not None else None,
                total_w=total_w)
            if checkpoint_every and (iteration + 1) % checkpoint_every == 0:
                self.checkpoint_segments_ += 1
                self._write_autockpt(checkpoint_path, iteration + 1)
            if max_shift < self.tolerance:
                log.converged(iteration + 1)
                break
        if checkpoint_every and self.iterations_run % checkpoint_every:
            self.checkpoint_segments_ += 1
            self._write_autockpt(checkpoint_path, self.iterations_run)
        # labels_ stays LAZY here (first access runs one full-X pass):
        # mini-batch training deliberately avoids full-N passes, and
        # _fit_ds is the host array — no device memory is pinned.
        return self

    def _incremental_update(self, batch: np.ndarray, centroids: np.ndarray,
                            seen: np.ndarray, iteration: int,
                            log: IterationLogger, sse_scale: float = 1.0,
                            batch_weight=None, total_w=None):
        """One Sculley update from one HOST batch: fused stats on device,
        then the count-weighted interpolation.  Used by the host sampling
        engine and ``partial_fit`` (caller-provided batches).
        ``batch_weight`` scales the batch's statistics; ``total_w`` (the
        dataset's total weight) switches the SSE estimate to the
        weighted scale factor ``total_w / batch_weight_sum`` (for
        unweighted data that reduces to the old ``n / bs``).

        Reassignment candidates are drawn on the host from THIS batch
        (seeded by ``[seed, iteration]`` — a different stream than the
        device engine's Gumbel draw, consistent with the engines' already-
        incomparable batch streams)."""
        bs, d = batch.shape
        mesh, model_shards, step_fn, _, chunk = self._setup(bs, d)
        from kmeans_tpu.parallel.sharding import shard_points
        pts, w = shard_points(batch, mesh, chunk,
                              sample_weight=batch_weight)
        stats = step_fn(pts, w, self._put_centroids(
            centroids.astype(self.dtype), mesh, model_shards))
        sums = np.asarray(stats.sums, dtype=np.float64)[: self.k]
        counts = np.asarray(stats.counts, dtype=np.float64)[: self.k]
        if total_w is not None:
            sse_scale = total_w / max(float(counts.sum()), 1.0)
        candidates = None
        do_re = self.reassignment_ratio > 0 and \
            (iteration + 1) % self._reassign_every(bs) == 0
        if do_re:
            rng = np.random.default_rng([self.seed, iteration, 0xC4ED])
            # Only positive-weight rows are eligible replacement centers
            # (the device engine's _batch_candidates masks bw > 0 too);
            # the unweighted draw stream is unchanged (elig = arange).
            elig = (np.arange(bs) if batch_weight is None
                    else np.flatnonzero(np.asarray(batch_weight) > 0))
            take = min(self.k, len(elig))
            if take:
                idx = elig[rng.choice(len(elig), size=take,
                                      replace=False)]
                candidates = batch[idx].astype(np.float64)
        return self._apply_batch_stats(sums, counts, centroids, seen,
                                       iteration, log,
                                       sse=float(stats.sse),
                                       sse_scale=sse_scale,
                                       candidates=candidates,
                                       do_reassign=do_re)

    def _apply_batch_stats(self, sums: np.ndarray, counts: np.ndarray,
                           centroids: np.ndarray, seen: np.ndarray,
                           iteration: int, log: IterationLogger, *,
                           sse: float, sse_scale: float,
                           candidates=None, cand_valid=None,
                           do_reassign: bool = False):
        """Host-side Sculley update from one batch's (sums, counts, sse):
        per-center count-weighted interpolation with lifetime ``seen``
        counts, guards and logging shared by both sampling engines.

        ``candidates``/``cand_valid``/``do_reassign`` carry the low-count
        reassignment inputs: when gated on, centers with
        ``seen < reassignment_ratio * seen.max()`` take candidate rows (in
        slot order) and reset their count to the kept centers' minimum —
        the same rule ``parallel.distributed.apply_reassignment`` runs in
        the one-dispatch loop, so the two engines' trajectories agree."""
        seen += counts
        eta = np.divide(counts, np.maximum(seen, 1.0))[:, None]
        batch_mean = sums / np.maximum(counts, 1.0)[:, None]
        new_centroids = np.where(
            counts[:, None] > 0,
            (1.0 - eta) * centroids + eta * batch_mean, centroids)

        if do_reassign and candidates is not None \
                and self.reassignment_ratio > 0:
            flagged = seen < self.reassignment_ratio * seen.max()
            n_valid = int(np.sum(cand_valid)) if cand_valid is not None \
                else len(candidates)
            slots = np.flatnonzero(flagged)[:n_valid]
            if slots.size:
                log.warn_reassign(slots.size)
                new_centroids[slots] = np.asarray(
                    candidates[: slots.size], dtype=np.float64)
                kept = seen[~flagged]
                seen[slots] = kept.min() if kept.size else 0.0

        if not np.all(np.isfinite(new_centroids)):
            self._raise_divergence("centroids", iteration + 1)
        if self.compute_sse:
            self.sse_history.append(sse * sse_scale)  # scaled batch estimate

        max_shift = float(np.max(np.linalg.norm(
            new_centroids - centroids, axis=1)))
        log.iteration(iteration, max_shift, counts.astype(np.int64),
                      self.sse_history[-1] if
                      (self.compute_sse and self.sse_history) else None)

        self.centroids = new_centroids.astype(self.dtype)
        self._centroids_f64 = np.asarray(new_centroids, dtype=np.float64)
        self.cluster_sizes_ = counts.astype(np.int64)
        self.iterations_run = iteration + 1
        self._seen = seen.copy()
        # Heartbeat (ISSUE 11): both mini-batch host loops finish their
        # iteration here — state is host-side already, zero extra
        # dispatches (no-op with no heartbeat installed).
        obs_note_progress(self, phase="iteration",
                                    shift=max_shift)
        return new_centroids, seen, max_shift

    def partial_fit(self, X, y=None, *,
                    sample_weight=None) -> "MiniBatchKMeans":
        """One incremental update from a caller-provided batch (sklearn's
        streaming API — beyond the reference, which has no incremental
        path).  First call initializes centroids from the batch; subsequent
        calls keep refining with lifetime per-center counts."""
        if sample_weight is not None:
            raise ValueError("partial_fit does not support sample_weight; "
                             "fold weights into batch construction")
        # partial_fit is not a checkpointed session: clear any ownership
        # flags a previous fit() left, so a diverging batch raises in
        # place instead of rolling the model back to that fit's stale
        # checkpoint and destroying the incremental progress (review
        # r10).
        self._active_ckpt_path = None
        self._ckpt_written_this_fit = False
        # partial_fit's SSE estimate is BATCH-scale (sse_scale=1), so
        # the quality-profile denominator falls back to the batch
        # counts — a stale full-fit total would inflate the reference.
        self._profile_total_w = None
        X = np.ascontiguousarray(np.asarray(X, dtype=self.dtype))
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        import jax
        log = IterationLogger(self.verbose and jax.process_index() == 0)
        if self.centroids is None:
            centroids = resolve_init(
                self.init, X, self.k, self.seed).astype(np.float64)
            self.sse_history = []
            self.iterations_run = 0
            self._seen = np.zeros(self.k)
        else:
            centroids = np.asarray(self.centroids, dtype=np.float64)
            if X.shape[1] != centroids.shape[1]:
                raise ValueError(
                    f"X has {X.shape[1]} features, but model was fitted "
                    f"with {centroids.shape[1]}")
        seen = np.asarray(self._seen, dtype=np.float64)
        self._incremental_update(X, centroids, seen,
                                 self.iterations_run, log)
        # labels for THIS batch under the updated centroids (sklearn
        # semantics: partial_fit leaves labels_ of the last batch).
        self._set_fit_data(X)
        return self

    def _learn_clone(self) -> "MiniBatchKMeans":
        """Detached working copy for the serve-and-learn actuator
        (ISSUE 20): ``partial_fit`` on the clone must never mutate THIS
        model's state, because this model keeps serving concurrently
        while the clone absorbs reservoir batches off the dispatch
        path.

        A shallow copy shares everything immutable-by-convention (the
        mesh — so the clone reuses the SAME compiled step programs,
        the zero-new-compiles contract — and the constructor config)
        while the mutable training state gets fresh copies.  The one
        aliasing hazard is ``_seen``: ``partial_fit`` feeds it through
        ``np.asarray(..., float64)`` — a NO-COPY passthrough for a
        float64 array — and ``_apply_batch_stats`` then updates it IN
        PLACE (``seen += counts``), so a shared array would corrupt
        the serving model's lifetime counts mid-update.

        NOT ``copy.copy``: that routes through ``__getstate__``, which
        materializes ``labels_`` — a full-dataset predict on the
        fit-time mesh, i.e. a surprise giant dispatch inside the
        background update (and a hard failure when the engine has
        re-pointed ``mesh`` since fit)."""
        if self.centroids is None:
            raise ValueError("_learn_clone requires a fitted model")
        clone = object.__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone.centroids = np.array(self.centroids, copy=True)
        carried = getattr(self, "_centroids_f64", None)
        clone._centroids_f64 = (np.array(carried, np.float64, copy=True)
                                if carried is not None else None)
        clone._seen = np.array(getattr(self, "_seen", np.zeros(self.k)),
                               dtype=np.float64, copy=True)
        clone.sse_history = list(self.sse_history)
        sizes = getattr(self, "cluster_sizes_", None)
        if sizes is not None:
            clone.cluster_sizes_ = np.array(sizes, copy=True)
        # The device-table cache is identity-keyed serving state, not
        # training state: the clone places its own tables on first use
        # and must never overwrite the serving model's entry.
        clone._cents_cache = None
        clone._fit_ds = None
        clone._labels_cache = None
        # Update batches run on a background thread while the original
        # serves traffic; per-iteration prints there would interleave
        # with serving output (and verbosity never touches the math).
        clone.verbose = False
        return clone

    def fit_stream(self, make_blocks, *, d=None, resume=False,
                   prefetch=2, **kwargs):
        """Blocked: the inherited exact-Lloyd ``fit_stream`` would silently
        bypass mini-batch semantics (ADVICE r1).  For streaming, feed blocks
        through ``partial_fit``; for an exact bigger-than-memory fit, use
        ``KMeans.fit_stream``."""
        raise NotImplementedError(
            "MiniBatchKMeans does not support fit_stream (it would run "
            "exact full-batch Lloyd, not mini-batch updates); stream blocks "
            "through partial_fit, or use KMeans.fit_stream for an exact "
            "out-of-core fit")

    def _profile_counts(self):
        """Quality-profile assignment mass (ISSUE 14): the LIFETIME
        per-center counts (``_seen``) rather than the last batch's
        ``cluster_sizes_`` — a 4096-row batch histogram is too noisy
        to be the drift reference, while the Sculley lifetime counts
        are exactly the training mass the centers converged under."""
        seen = getattr(self, "_seen", None)
        if seen is not None and float(np.sum(seen)) > 0:
            return np.asarray(seen, np.float64)
        return self.cluster_sizes_

    def _profile_rows(self):
        """Score-per-row denominator (ISSUE 14, review finding):
        ``inertia_`` here is the TOTAL-WEIGHT-scaled SSE estimate, so
        the denominator is the dataset weight recorded at fit time —
        NOT the lifetime ``_seen`` total (rows processed = passes x
        batch; dividing by it deflates the reference by the pass
        count) and NOT ``cluster_sizes_`` (one batch).  partial_fit
        leaves it None: its estimate is batch-scale, and the base rule
        (the last batch's counts) is then the matching denominator."""
        if self._profile_total_w:
            return float(self._profile_total_w)
        return super()._profile_rows()

    def _state_dict(self) -> dict:
        state = super()._state_dict()
        # The denominator must round-trip (ISSUE 14): ``_seen`` does,
        # so a LOADED model re-derives the same profile from attrs —
        # without this its score reference would silently vanish.
        state["profile_total_w"] = self._profile_total_w
        state["batch_size"] = self.batch_size
        state["sampling"] = self.sampling
        state["reassignment_ratio"] = self.reassignment_ratio
        state["seen_counts"] = np.asarray(getattr(self, "_seen",
                                                  np.zeros(self.k)))
        carried = getattr(self, "_centroids_f64", None)
        if carried is not None:
            # The float64 Sculley carry (see _resume_or_init) — without
            # it a resumed float32 model restarts from the cast copy and
            # drifts off the uninterrupted trajectory by the cast error.
            state["centroids_f64"] = np.asarray(carried, np.float64)
        return state

    def _restore_state(self, state: dict) -> None:
        super()._restore_state(state)
        ptw = state.get("profile_total_w")
        self._profile_total_w = float(ptw) if ptw is not None else None
        self._seen = np.asarray(state["seen_counts"])
        carried = state.get("centroids_f64")
        # Explicitly clear on pre-carry checkpoints: a stale in-memory
        # carry from an earlier fit must not shadow the restored state.
        self._centroids_f64 = (np.asarray(carried, np.float64)
                               if carried is not None else None)

    @classmethod
    def _load_kwargs(cls, state: dict) -> dict:
        return {"batch_size": state["batch_size"],
                "sampling": state.get("sampling", "device"),
                # Checkpoints from before the feature resume with it OFF:
                # their uninterrupted trajectory never reassigned, and
                # resume continuity promises to reproduce it.
                "reassignment_ratio":
                    float(state.get("reassignment_ratio", 0.0))}
