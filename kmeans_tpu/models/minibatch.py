"""Mini-batch K-Means (beyond-reference superset).

The reference has only full-batch Lloyd iterations (kmeans_spark.py:266-313).
This variant (Sculley 2010-style) reuses the same fused SPMD step on a seeded
per-iteration sample and applies per-center count-weighted incremental
updates — useful when N is far larger than one pass per iteration justifies.
Shares every guard and logging behavior with :class:`KMeans`.
"""

from __future__ import annotations

import numpy as np

from kmeans_tpu.models.kmeans import KMeans
from kmeans_tpu.models.init import resolve_init
from kmeans_tpu.utils.logging import IterationLogger


class MiniBatchKMeans(KMeans):
    _PARAM_NAMES = KMeans._PARAM_NAMES + ("batch_size",)

    def __init__(self, k: int = 3, max_iter: int = 100,
                 tolerance: float = 1e-4, seed: int = 42,
                 compute_sse: bool = False, *, batch_size: int = 4096,
                 **kwargs):
        super().__init__(k, max_iter, tolerance, seed, compute_sse, **kwargs)
        if self.n_init != 1:
            raise ValueError("MiniBatchKMeans does not support n_init > 1; "
                             "run restarts explicitly and keep the best "
                             "inertia")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.batch_size = batch_size

    def fit(self, X, y=None, *, resume: bool = False) -> "MiniBatchKMeans":
        from kmeans_tpu.parallel.sharding import ShardedDataset
        if isinstance(X, ShardedDataset):
            if X.host is None:
                raise ValueError("MiniBatchKMeans needs host data to draw "
                                 "batches; pass a NumPy array")
            X = X.host
        X = np.ascontiguousarray(np.asarray(X, dtype=self.dtype))
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        n, d = X.shape
        bs = min(self.batch_size, n)
        self._set_fit_data(X)                         # feeds lazy labels_
        import jax
        log = IterationLogger(self.verbose and jax.process_index() == 0)

        if resume and self.centroids is not None:
            centroids = np.asarray(self.centroids, dtype=np.float64)
            start_iter = self.iterations_run
            seen = np.asarray(self._seen, dtype=np.float64)
        else:
            centroids = resolve_init(
                self.init, X, self.k, self.seed).astype(np.float64)
            self.sse_history = []
            self.iterations_run = 0
            start_iter = 0
            seen = np.zeros(self.k)    # lifetime per-center counts

        log.startup(self.k, self.max_iter, self.tolerance, self.compute_sse)

        for iteration in range(start_iter, self.max_iter):
            # Per-iteration derived RNG: batch i is a pure function of
            # (seed, i), so a checkpointed run resumes the SAME batch
            # sequence an uninterrupted run would see.
            rng = np.random.default_rng([self.seed, iteration])
            batch = X[rng.choice(n, size=bs, replace=False)]
            centroids, seen, max_shift = self._incremental_update(
                batch, centroids, seen, iteration, log, sse_scale=n / bs)
            if max_shift < self.tolerance:
                log.converged(iteration + 1)
                break
        # labels_ stays LAZY here (first access runs one full-X pass):
        # mini-batch training deliberately avoids full-N passes, and
        # _fit_ds is the host array — no device memory is pinned.
        return self

    def _incremental_update(self, batch: np.ndarray, centroids: np.ndarray,
                            seen: np.ndarray, iteration: int,
                            log: IterationLogger, sse_scale: float = 1.0):
        """One Sculley update from one batch: fused stats on device, then
        per-center count-weighted interpolation on the host.  Shared by
        ``fit`` (seeded internal batches) and ``partial_fit`` (caller-
        provided batches)."""
        bs, d = batch.shape
        mesh, model_shards, step_fn, _, chunk = self._setup(bs, d)
        from kmeans_tpu.parallel.sharding import shard_points
        pts, w = shard_points(batch, mesh, chunk)
        stats = step_fn(pts, w, self._put_centroids(
            centroids.astype(self.dtype), mesh, model_shards))
        sums = np.asarray(stats.sums, dtype=np.float64)[: self.k]
        counts = np.asarray(stats.counts, dtype=np.float64)[: self.k]

        seen += counts
        eta = np.divide(counts, np.maximum(seen, 1.0))[:, None]
        batch_mean = sums / np.maximum(counts, 1.0)[:, None]
        new_centroids = np.where(
            counts[:, None] > 0,
            (1.0 - eta) * centroids + eta * batch_mean, centroids)

        if not np.all(np.isfinite(new_centroids)):
            raise ValueError(
                f"NaN or Inf detected in centroids at iteration "
                f"{iteration + 1}")
        if self.compute_sse:
            sse = float(stats.sse) * sse_scale   # scaled batch estimate
            self.sse_history.append(sse)

        max_shift = float(np.max(np.linalg.norm(
            new_centroids - centroids, axis=1)))
        log.iteration(iteration, max_shift, counts.astype(np.int64),
                      self.sse_history[-1] if
                      (self.compute_sse and self.sse_history) else None)

        self.centroids = new_centroids.astype(self.dtype)
        self.cluster_sizes_ = counts.astype(np.int64)
        self.iterations_run = iteration + 1
        self._seen = seen.copy()
        return new_centroids, seen, max_shift

    def partial_fit(self, X, y=None, *,
                    sample_weight=None) -> "MiniBatchKMeans":
        """One incremental update from a caller-provided batch (sklearn's
        streaming API — beyond the reference, which has no incremental
        path).  First call initializes centroids from the batch; subsequent
        calls keep refining with lifetime per-center counts."""
        if sample_weight is not None:
            raise ValueError("partial_fit does not support sample_weight; "
                             "fold weights into batch construction")
        X = np.ascontiguousarray(np.asarray(X, dtype=self.dtype))
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
        import jax
        log = IterationLogger(self.verbose and jax.process_index() == 0)
        if self.centroids is None:
            centroids = resolve_init(
                self.init, X, self.k, self.seed).astype(np.float64)
            self.sse_history = []
            self.iterations_run = 0
            self._seen = np.zeros(self.k)
        else:
            centroids = np.asarray(self.centroids, dtype=np.float64)
            if X.shape[1] != centroids.shape[1]:
                raise ValueError(
                    f"X has {X.shape[1]} features, but model was fitted "
                    f"with {centroids.shape[1]}")
        seen = np.asarray(self._seen, dtype=np.float64)
        self._incremental_update(X, centroids, seen,
                                 self.iterations_run, log)
        # labels for THIS batch under the updated centroids (sklearn
        # semantics: partial_fit leaves labels_ of the last batch).
        self._set_fit_data(X)
        return self

    def fit_stream(self, make_blocks, *, d=None):
        """Blocked: the inherited exact-Lloyd ``fit_stream`` would silently
        bypass mini-batch semantics (ADVICE r1).  For streaming, feed blocks
        through ``partial_fit``; for an exact bigger-than-memory fit, use
        ``KMeans.fit_stream``."""
        raise NotImplementedError(
            "MiniBatchKMeans does not support fit_stream (it would run "
            "exact full-batch Lloyd, not mini-batch updates); stream blocks "
            "through partial_fit, or use KMeans.fit_stream for an exact "
            "out-of-core fit")

    def _state_dict(self) -> dict:
        state = super()._state_dict()
        state["batch_size"] = self.batch_size
        state["seen_counts"] = np.asarray(getattr(self, "_seen",
                                                  np.zeros(self.k)))
        return state

    def _restore_state(self, state: dict) -> None:
        super()._restore_state(state)
        self._seen = np.asarray(state["seen_counts"])

    @classmethod
    def _load_kwargs(cls, state: dict) -> dict:
        return {"batch_size": state["batch_size"]}
