"""Model layer: the user-facing K-Means estimator (reference L3).

The reference's single "model" is K-means itself (``class KMeans``,
kmeans_spark.py:19-352); this package holds its TPU-native re-design plus
initialization strategies (Forgy parity + kmeans++ superset) and a mini-batch
variant.
"""

from kmeans_tpu.models.kmeans import DispatchLatencyHint, KMeans
from kmeans_tpu.models.minibatch import MiniBatchKMeans
from kmeans_tpu.models.bisecting import BisectingKMeans
from kmeans_tpu.models.spherical import SphericalKMeans
from kmeans_tpu.models.gmm import GaussianMixture
from kmeans_tpu.models.fault_tolerance import NumericalDivergenceError
from kmeans_tpu.models.init import forgy_init, kmeanspp_init
from kmeans_tpu.models.pq import ProductQuantizer

__all__ = ["KMeans", "MiniBatchKMeans", "BisectingKMeans",
           "SphericalKMeans", "GaussianMixture", "DispatchLatencyHint",
           "NumericalDivergenceError", "forgy_init", "kmeanspp_init",
           "ProductQuantizer"]
