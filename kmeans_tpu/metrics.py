"""Clustering quality metrics, chunked for TPU.

Beyond-reference capability (the reference's only quality metric is total
SSE, ``_compute_sse``, kmeans_spark.py:208-237): the standard internal
cluster-validity scores, designed the same way as the training step —
fixed-size chunks under ``lax.scan``, distances in the matmul form so the
O(n²D) / O(nkD) work lands on the MXU, per-cluster reductions as one-hot
matmuls instead of segment gathers.

All functions take host arrays, run as ``shard_map`` passes with the row
axis sharded over the mesh's data axis (``mesh=None`` builds one over
every visible device — a 1-device mesh is the plain single-chip case),
and are validated against scikit-learn's implementations in
``tests/test_metrics.py`` (sklearn stays a test-only oracle, the
reference's own policy — README.md:13).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.ops.assign import pairwise_sq_dists
from kmeans_tpu.utils.validation import check_finite_array

__all__ = ["silhouette_score", "silhouette_samples",
           "davies_bouldin_score", "calinski_harabasz_score",
           "adjusted_rand_score", "mutual_info_score",
           "normalized_mutual_info_score",
           "homogeneity_completeness_v_measure",
           "batched_criterion_scores"]

#: Device dispatches one ``batched_criterion_scores`` call costs —
#: CONSTANT in the number of members (the sweep engine's O(1)-dispatch
#: accounting, ISSUE 7): silhouette is one row-sharded pass; CH/DB are
#: one batched moments pass + one batched scatter pass.
SWEEP_SCORE_DISPATCHES = {"silhouette": 1, "calinski_harabasz": 2,
                          "davies_bouldin": 2}


def _as_arrays(X, labels):
    X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
    labels = np.asarray(labels)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
    if labels.shape != (X.shape[0],):
        raise ValueError(f"labels must have shape ({X.shape[0]},), got "
                         f"{labels.shape}")
    check_finite_array(X, "Input data contains NaN or Inf values")
    # Compact to 0..k-1 over the ids actually present (sklearn's
    # LabelEncoder behavior): gapped ids — an emptied cluster under
    # ``empty_cluster='keep'``, or DBSCAN-style ``-1`` noise — must not
    # become phantom origin clusters in the one-hot reductions.
    uniq, enc = np.unique(labels, return_inverse=True)
    k = int(uniq.size)
    if k < 2 or k >= X.shape[0]:
        raise ValueError("metrics need 2 <= n_labels <= n_samples - 1 "
                         f"(got {k} distinct labels, {X.shape[0]} samples)")
    labels = np.ascontiguousarray(enc.astype(np.int32))
    return X, labels, k


def _pad_chunks(X, labels, chunk: int):
    n = X.shape[0]
    pad = (-n) % chunk
    Xp = np.pad(X, ((0, pad), (0, 0)))
    # Padding rows get label -1: their one-hot row is all-zero, so they
    # contribute to nothing.  Returned as HOST arrays — callers place
    # them exactly once (sharded) per score.
    lp = np.pad(labels, (0, pad), constant_values=-1)
    return Xp, lp, n


# Built shard_map passes for the O(n*k*D) reductions, keyed like
# _SIL_CACHE — the O(n) row axis shards over the mesh's data axis, so
# these scale exactly like the training step (r3: previously
# single-device jits).
_MOM_CACHE: dict = {}


def _sharded_reduction(mesh, k: int, chunk: int, kind: str):
    from jax.sharding import PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS, shard_map
    key = (mesh, k, chunk, kind)
    if key in _MOM_CACHE:
        return _MOM_CACHE[key]

    if kind == "moments":
        def run(xrows, lrows):
            d = xrows.shape[1]
            xs = (xrows.reshape(-1, chunk, d), lrows.reshape(-1, chunk))

            def body(carry, args):
                sums, counts = carry
                xc, lc = args
                onehot = (lc[:, None] == jnp.arange(k)[None, :]) \
                    .astype(jnp.float32)
                return (sums + jnp.einsum("ck,cd->kd", onehot, xc),
                        counts + jnp.sum(onehot, axis=0)), None

            a, b = lax.scan(body, (jnp.zeros((k, d)), jnp.zeros((k,))),
                            xs)[0]
            return lax.psum(a, DATA_AXIS), lax.psum(b, DATA_AXIS)

        in_specs = (P(DATA_AXIS, None), P(DATA_AXIS))
        out_specs = (P(None, None), P(None))
    else:                # per-cluster distance sums to own centroid
        def run(xrows, lrows, centroids):
            d = xrows.shape[1]
            xs = (xrows.reshape(-1, chunk, d), lrows.reshape(-1, chunk))

            def body(carry, args):
                s1, s2 = carry
                xc, lc = args
                d2 = pairwise_sq_dists(xc, centroids)      # (chunk, k)
                onehot = (lc[:, None] == jnp.arange(k)[None, :]) \
                    .astype(jnp.float32)
                own_d2 = jnp.sum(d2 * onehot, axis=1)
                return (s1 + jnp.einsum("ck,c->k", onehot,
                                        jnp.sqrt(own_d2)),
                        s2 + jnp.einsum("ck,c->k", onehot, own_d2)), None

            a, b = lax.scan(body, (jnp.zeros((k,)), jnp.zeros((k,))),
                            xs)[0]
            return lax.psum(a, DATA_AXIS), lax.psum(b, DATA_AXIS)

        in_specs = (P(DATA_AXIS, None), P(DATA_AXIS), P(None, None))
        out_specs = (P(None), P(None))

    mapped = shard_map(run, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    _MOM_CACHE[key] = jax.jit(mapped)
    return _MOM_CACHE[key]


def _place_rows(mesh, Xp, lp):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS
    return (jax.device_put(np.asarray(Xp),
                           NamedSharding(mesh, P(DATA_AXIS, None))),
            jax.device_put(np.asarray(lp),
                           NamedSharding(mesh, P(DATA_AXIS))))


def _cluster_moments(mesh, xr, lr, k: int, chunk: int):
    """Per-cluster (coordinate-sum, count) from PLACED rows."""
    return _sharded_reduction(mesh, k, chunk, "moments")(xr, lr)


def _scatter_to_centroids(mesh, xr, lr, centroids, k: int, chunk: int):
    """Per-cluster sums of EUCLIDEAN distance and squared distance from
    each member to its own centroid, from PLACED rows."""
    return _sharded_reduction(mesh, k, chunk, "scatter")(xr, lr, centroids)


def _mesh_and_chunk(X, mesh, lo: int = 256, hi: int = 2048):
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    if mesh is None:
        mesh = make_mesh()
    data_shards, _ = mesh_shape(mesh)
    chunk = min(hi, max(lo, -(-X.shape[0] // data_shards)))
    return mesh, data_shards, chunk


def davies_bouldin_score(X, labels, *, mesh=None) -> float:
    """Davies-Bouldin index (lower is better), row-sharded over the mesh.

    DB = mean_i max_{j!=i} (s_i + s_j) / d(c_i, c_j) with s_i the mean
    Euclidean distance of cluster i's members to its centroid.
    """
    X, labels, k = _as_arrays(X, labels)
    mesh, data_shards, chunk = _mesh_and_chunk(X, mesh)
    Xp, lp, n = _pad_chunks(X, labels, data_shards * chunk)
    xr, lr = _place_rows(mesh, Xp, lp)          # placed ONCE, reused
    sums, counts = _cluster_moments(mesh, xr, lr, k, chunk)
    counts = np.asarray(counts, np.float64)
    centroids = np.asarray(sums, np.float64) / np.maximum(counts, 1.0)[:, None]
    s1, _ = _scatter_to_centroids(mesh, xr, lr,
                                  jnp.asarray(centroids, jnp.float32),
                                  k, chunk)
    scatter = np.asarray(s1, np.float64) / np.maximum(counts, 1.0)
    cd = np.sqrt(np.maximum(np.asarray(
        pairwise_sq_dists(jnp.asarray(centroids, jnp.float32),
                          jnp.asarray(centroids, jnp.float32), mode="direct"),
        np.float64), 0.0))
    ratio = (scatter[:, None] + scatter[None, :]) / np.where(cd > 0, cd, np.inf)
    np.fill_diagonal(ratio, 0.0)
    return float(np.mean(ratio.max(axis=1)))


def calinski_harabasz_score(X, labels, *, mesh=None) -> float:
    """Calinski-Harabasz index / variance-ratio criterion (higher is
    better): (between-group SS / (k-1)) / (within-group SS / (n-k)).
    Row-sharded over the mesh."""
    X, labels, k = _as_arrays(X, labels)
    mesh, data_shards, chunk = _mesh_and_chunk(X, mesh)
    Xp, lp, n = _pad_chunks(X, labels, data_shards * chunk)
    xr, lr = _place_rows(mesh, Xp, lp)          # placed ONCE, reused
    sums, counts = _cluster_moments(mesh, xr, lr, k, chunk)
    counts = np.asarray(counts, np.float64)
    sums = np.asarray(sums, np.float64)
    centroids = sums / np.maximum(counts, 1.0)[:, None]
    _, s2 = _scatter_to_centroids(mesh, xr, lr,
                                  jnp.asarray(centroids, jnp.float32),
                                  k, chunk)
    wss = float(np.sum(np.asarray(s2, np.float64)))
    mean = sums.sum(axis=0) / n
    bss = float(np.sum(counts * np.sum((centroids - mean) ** 2, axis=1)))
    if wss == 0.0:
        return 1.0                                  # sklearn's degenerate case
    return float(bss * (n - k) / (wss * (k - 1)))


def _silhouette_chunk(xc, lc, Xp, lp, counts, k: int, col_block: int):
    """Silhouette values for one row chunk: column-blocked passes over
    the full point set — each step materializes only a
    (chunk, col_block) distance tile (matmul form, MXU) and reduces it
    to per-cluster sums with an on-the-fly one-hot (col_block, k)
    matmul, so NOTHING of O(n*k) or O(n^2) size ever exists at once."""
    d = Xp.shape[1]
    cols = (Xp.reshape(-1, col_block, d), lp.reshape(-1, col_block))

    def cbody(csums, args):
        xb, lb = args
        dist = jnp.sqrt(pairwise_sq_dists(xc, xb))     # (chunk, cb)
        oh = (lb[:, None] == jnp.arange(k)[None, :]).astype(jnp.float32)
        return csums + dist @ oh, None

    csums, _ = lax.scan(
        cbody, jnp.zeros((xc.shape[0], k), jnp.float32), cols)
    own = jnp.take_along_axis(csums, lc[:, None].clip(0), axis=1)[:, 0]
    own_count = counts[lc.clip(0)]
    # a: mean distance to OWN cluster, self excluded (|C|-1 denominator).
    a = own / jnp.maximum(own_count - 1.0, 1.0)
    # b: min over OTHER clusters of mean distance.
    mean_other = csums / jnp.maximum(counts, 1.0)[None, :]
    mask_own = (lc[:, None] == jnp.arange(k)[None, :])
    mean_other = jnp.where(mask_own | (counts[None, :] == 0),
                           jnp.inf, mean_other)
    b = jnp.min(mean_other, axis=1)
    return jnp.where(own_count <= 1.0, 0.0,
                     (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30))


# Built shard_map passes, keyed by everything that forces a retrace —
# without this every silhouette call would pay a full compile.
_SIL_CACHE: dict = {}


def _silhouette_mesh_fn(mesh, k: int, chunk: int, col_block: int):
    """Build (or fetch) the row-sharded silhouette pass: the O(n^2 D)
    distance work is split over the mesh's data axis (each shard scores
    ITS rows against a replicated copy of all points — compute scales
    1/shards, per-device memory stays O(n*D + chunk*col_block), r2
    VERDICT weak #5).  The quadratic-compute regime this targets is
    exactly where the O(n*D) replica is small."""
    key = (mesh, k, chunk, col_block)
    if key in _SIL_CACHE:
        return _SIL_CACHE[key]
    from jax.sharding import PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS, shard_map

    def run(xrows, lrows, Xfull, lfull, counts):
        nc = xrows.shape[0] // chunk
        xs = (xrows.reshape(nc, chunk, -1), lrows.reshape(nc, chunk))

        def body(_, args):
            xc, lc = args
            return None, _silhouette_chunk(xc, lc, Xfull, lfull, counts,
                                           k, col_block)

        _, s = lax.scan(body, None, xs)
        return s.reshape(-1)

    mapped = shard_map(
        run, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None, None),
                  P(None), P(None)),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    _SIL_CACHE[key] = jax.jit(mapped)
    return _SIL_CACHE[key]


def silhouette_samples(X, labels, *, mesh=None) -> np.ndarray:
    """Per-point silhouette coefficient (b - a) / max(a, b); singleton
    clusters score 0 (sklearn convention).  ``mesh=None`` builds a
    data-axis mesh over every visible device; the O(n^2 D) pass is
    row-sharded across it."""
    X, labels, k = _as_arrays(X, labels)
    mesh, data_shards, chunk = _mesh_and_chunk(X, mesh, lo=128, hi=1024)
    col_block = min(4096, max(256, X.shape[0]))
    # Rows pad to a whole number of chunks per shard; columns to a whole
    # number of blocks.  Padding rows carry label -1 -> all-zero one-hot.
    Xr, lr, n = _pad_chunks(X, labels, data_shards * chunk)
    Xc, lc, _ = _pad_chunks(X, labels, col_block)
    counts = jnp.asarray(np.bincount(labels, minlength=k)
                         .astype(np.float32))
    fn = _silhouette_mesh_fn(mesh, k, chunk, col_block)
    xr, lrp = _place_rows(mesh, Xr, lr)
    s = fn(xr, lrp, Xc, lc, counts)
    return np.asarray(s, dtype=np.float64)[:n]


def silhouette_score(X, labels, *, sample_size: Optional[int] = None,
                     seed: int = 0, mesh=None) -> float:
    """Mean silhouette coefficient over all points (or a seeded
    ``sample_size`` subsample for large n — the full score is O(n²D))."""
    X = np.asarray(X)
    labels = np.asarray(labels)
    if sample_size is not None and sample_size < X.shape[0]:
        idx = np.random.default_rng(seed).choice(
            X.shape[0], size=sample_size, replace=False)
        X, labels = X[idx], labels[idx]
    return float(np.mean(silhouette_samples(X, labels, mesh=mesh)))


# ---------------------------------------------------- batched (sweep) scoring
# The model-selection sweep's scoring half (ISSUE 7): score M label sets
# over the SAME rows in a CONSTANT number of device dispatches — the
# member axis is batched into the reductions exactly like the sweep's
# fit batches the restart/k axis, so criterion scoring never costs M
# host round trips.  Each member may use a different number of clusters;
# everything is padded to the stack's k_max with all-zero one-hot rows
# (absent cluster ids simply have zero counts and are compacted away in
# the host finishing, matching the single-member functions' LabelEncoder
# behavior bit-for-bit on the present clusters).


def _as_arrays_batched(X, labels_stack):
    X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
    L = np.asarray(labels_stack)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
    if L.ndim != 2 or L.shape[1] != X.shape[0]:
        raise ValueError(f"labels_stack must have shape (M, {X.shape[0]}),"
                         f" got {L.shape}")
    check_finite_array(X, "Input data contains NaN or Inf values")
    if np.any(L < 0):
        raise ValueError("batched labels must be non-negative ints "
                         "(one compact label set per member)")
    L = np.ascontiguousarray(L.astype(np.int32))
    k_max = int(L.max()) + 1
    # ONE bincount pass serves both the validity rule and the
    # silhouette counts (an np.unique-per-member loop here re-sorted
    # every label row just to count distinct values).  A member outside
    # 2 <= n_labels <= n_samples - 1 does not abort the sweep — it
    # scores NaN (select_k masks non-finite scores; a k that collapsed
    # under empty_cluster='keep' is an ANSWER about that k, and the
    # other members' scores must survive it).
    counts = np.stack([np.bincount(L[m], minlength=k_max)
                       for m in range(L.shape[0])])
    occupied = (counts > 0).sum(axis=1)
    valid = (occupied >= 2) & (occupied <= X.shape[0] - 1)
    return X, L, k_max, counts, valid


def _pad_chunks_batched(X, L, chunk: int):
    n = X.shape[0]
    pad = (-n) % chunk
    Xp = np.pad(X, ((0, pad), (0, 0)))
    Lp = np.pad(L, ((0, 0), (0, pad)), constant_values=-1)
    return Xp, Lp, n


def _sharded_reduction_batched(mesh, M: int, k: int, chunk: int,
                               kind: str):
    """The batched twins of ``_sharded_reduction``: labels carry a
    leading member axis (M, n) and the one-hot reductions batch over it
    in the SAME row-sharded pass — one dispatch scores every member."""
    from jax.sharding import PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS, shard_map
    key = (mesh, M, k, chunk, "batched_" + kind)
    if key in _MOM_CACHE:
        return _MOM_CACHE[key]
    ids = jnp.arange(k)

    if kind == "moments":
        def run(xrows, lrows):
            d = xrows.shape[1]
            nc = xrows.shape[0] // chunk
            xs = (xrows.reshape(nc, chunk, d),
                  jnp.moveaxis(lrows.reshape(M, nc, chunk), 1, 0))

            def body(carry, args):
                sums, counts = carry
                xc, lcs = args                           # (M, chunk)
                oh = (lcs[:, :, None] == ids[None, None, :]) \
                    .astype(jnp.float32)                 # (M, chunk, k)
                return (sums + jnp.einsum("mck,cd->mkd", oh, xc),
                        counts + jnp.sum(oh, axis=1)), None

            a, b = lax.scan(body, (jnp.zeros((M, k, xrows.shape[1])),
                                   jnp.zeros((M, k))), xs)[0]
            return lax.psum(a, DATA_AXIS), lax.psum(b, DATA_AXIS)

        in_specs = (P(DATA_AXIS, None), P(None, DATA_AXIS))
        out_specs = (P(None, None, None), P(None, None))
    else:                # per-cluster distance sums to own centroid
        def run(xrows, lrows, centroids):                # (M, k, d)
            d = xrows.shape[1]
            nc = xrows.shape[0] // chunk
            xs = (xrows.reshape(nc, chunk, d),
                  jnp.moveaxis(lrows.reshape(M, nc, chunk), 1, 0))

            def body(carry, args):
                s1, s2 = carry
                xc, lcs = args
                d2 = jax.vmap(
                    lambda cb: pairwise_sq_dists(xc, cb))(centroids)
                oh = (lcs[:, :, None] == ids[None, None, :]) \
                    .astype(jnp.float32)                 # (M, chunk, k)
                own_d2 = jnp.sum(d2 * oh, axis=2)        # (M, chunk)
                return (s1 + jnp.einsum("mck,mc->mk", oh,
                                        jnp.sqrt(own_d2)),
                        s2 + jnp.einsum("mck,mc->mk", oh, own_d2)), None

            a, b = lax.scan(body, (jnp.zeros((M, k)), jnp.zeros((M, k))),
                            xs)[0]
            return lax.psum(a, DATA_AXIS), lax.psum(b, DATA_AXIS)

        in_specs = (P(DATA_AXIS, None), P(None, DATA_AXIS),
                    P(None, None, None))
        out_specs = (P(None, None), P(None, None))

    mapped = shard_map(run, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    _MOM_CACHE[key] = jax.jit(mapped)
    return _MOM_CACHE[key]


def _place_rows_batched(mesh, Xp, Lp):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS
    return (jax.device_put(np.asarray(Xp),
                           NamedSharding(mesh, P(DATA_AXIS, None))),
            jax.device_put(np.asarray(Lp),
                           NamedSharding(mesh, P(None, DATA_AXIS))))


def _batched_moments_and_scatter(X, L, k, mesh):
    """(sums (M,k,d), counts (M,k), s1 (M,k), s2 (M,k)) in exactly TWO
    row-sharded dispatches — the shared engine of the batched CH/DB
    scores."""
    M = L.shape[0]
    mesh, data_shards, chunk = _mesh_and_chunk(X, mesh)
    Xp, Lp, n = _pad_chunks_batched(X, L, data_shards * chunk)
    xr, lr = _place_rows_batched(mesh, Xp, Lp)
    sums, counts = _sharded_reduction_batched(
        mesh, M, k, chunk, "moments")(xr, lr)
    counts = np.asarray(counts, np.float64)
    sums = np.asarray(sums, np.float64)
    centroids = sums / np.maximum(counts, 1.0)[..., None]
    s1, s2 = _sharded_reduction_batched(mesh, M, k, chunk, "scatter")(
        xr, lr, jnp.asarray(centroids, jnp.float32))
    return (sums, counts, centroids, np.asarray(s1, np.float64),
            np.asarray(s2, np.float64), n)


def _silhouette_chunk_batched(xc, lcs, Xp, lps, counts, k: int,
                              col_block: int):
    """Member-batched ``_silhouette_chunk``: the (chunk, col_block)
    distance tile is computed ONCE and reduced against every member's
    one-hot — M label sets share one O(n^2 D) pass instead of running
    it M times."""
    d = Xp.shape[1]
    M = lcs.shape[0]
    ncb = Xp.shape[0] // col_block
    ids = jnp.arange(k)
    cols = (Xp.reshape(ncb, col_block, d),
            jnp.moveaxis(lps.reshape(M, ncb, col_block), 1, 0))

    def cbody(csums, args):
        xb, lbs = args                                   # (M, cb)
        dist = jnp.sqrt(pairwise_sq_dists(xc, xb))       # (chunk, cb)
        oh = (lbs[:, :, None] == ids[None, None, :]) \
            .astype(jnp.float32)                         # (M, cb, k)
        return csums + jnp.einsum("cb,mbk->mck", dist, oh), None

    csums, _ = lax.scan(
        cbody, jnp.zeros((M, xc.shape[0], k), jnp.float32), cols)
    own = jnp.take_along_axis(csums, lcs[:, :, None].clip(0),
                              axis=2)[:, :, 0]           # (M, chunk)
    own_count = jnp.take_along_axis(counts, lcs.clip(0), axis=1)
    a = own / jnp.maximum(own_count - 1.0, 1.0)
    mean_other = csums / jnp.maximum(counts, 1.0)[:, None, :]
    mask_own = (lcs[:, :, None] == ids[None, None, :])
    mean_other = jnp.where(mask_own | (counts[:, None, :] == 0),
                           jnp.inf, mean_other)
    b = jnp.min(mean_other, axis=2)
    return jnp.where(own_count <= 1.0, 0.0,
                     (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30))


def _silhouette_mesh_fn_batched(mesh, M: int, k: int, chunk: int,
                                col_block: int):
    key = (mesh, M, k, chunk, col_block, "batched")
    if key in _SIL_CACHE:
        return _SIL_CACHE[key]
    from jax.sharding import PartitionSpec as P
    from kmeans_tpu.parallel.mesh import DATA_AXIS, shard_map

    def run(xrows, lrows, Xfull, lfull, counts):
        nc = xrows.shape[0] // chunk
        xs = (xrows.reshape(nc, chunk, -1),
              jnp.moveaxis(lrows.reshape(M, nc, chunk), 1, 0))

        def body(_, args):
            xc, lcs = args
            return None, _silhouette_chunk_batched(
                xc, lcs, Xfull, lfull, counts, k, col_block)

        _, s = lax.scan(body, None, xs)                  # (nc, M, chunk)
        return jnp.moveaxis(s, 1, 0).reshape(M, -1)

    mapped = shard_map(
        run, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None, DATA_AXIS), P(None, None),
                  P(None, None), P(None, None)),
        out_specs=P(None, DATA_AXIS),
        check_vma=False)
    _SIL_CACHE[key] = jax.jit(mapped)
    return _SIL_CACHE[key]


def batched_criterion_scores(X, labels_stack, criterion: str, *,
                             mesh=None, sample_size: Optional[int] = None,
                             seed: int = 0) -> np.ndarray:
    """Score M label sets over the same rows in O(1) dispatches.

    ``labels_stack`` is (M, n) — e.g. every sweep winner's labels from
    one packed-model assignment dispatch.  ``criterion`` is
    ``'silhouette'`` (one member-batched row-sharded O(n^2 D) pass;
    ``sample_size`` subsamples the SAME seeded rows for every member,
    like ``silhouette_score``), ``'calinski_harabasz'`` or
    ``'davies_bouldin'`` (one batched moments pass + one batched scatter
    pass, host finishing per member).  Returns (M,) float64 scores that
    match the single-member functions on each row of the stack
    (``tests/test_sweep.py`` pins the parity)."""
    if criterion not in SWEEP_SCORE_DISPATCHES:
        raise ValueError(f"unknown batched criterion {criterion!r}; "
                         f"valid: {sorted(SWEEP_SCORE_DISPATCHES)}")
    if criterion == "silhouette":
        X = np.asarray(X)
        L = np.asarray(labels_stack)
        if sample_size is not None and sample_size < X.shape[0]:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], size=sample_size, replace=False)
            X, L = X[idx], L[:, idx]
        X, L, k, member_counts, valid = _as_arrays_batched(X, L)
        M = L.shape[0]
        mesh, data_shards, chunk = _mesh_and_chunk(X, mesh, lo=128,
                                                   hi=1024)
        col_block = min(4096, max(256, X.shape[0]))
        Xr, Lr, n = _pad_chunks_batched(X, L, data_shards * chunk)
        Xc, Lc, _ = _pad_chunks_batched(X, L, col_block)
        counts = jnp.asarray(member_counts.astype(np.float32))
        fn = _silhouette_mesh_fn_batched(mesh, M, k, chunk, col_block)
        xr, lr = _place_rows_batched(mesh, Xr, Lr)
        s = np.asarray(fn(xr, lr, Xc, Lc, counts), np.float64)[:, :n]
        out = s.mean(axis=1)
        out[~valid] = np.nan
        return out

    X, L, k, _, valid = _as_arrays_batched(X, labels_stack)
    sums, counts, centroids, s1, s2, n = _batched_moments_and_scatter(
        X, L, k, mesh)
    M = L.shape[0]
    out = np.empty((M,), np.float64)
    for m in range(M):
        if not valid[m]:
            out[m] = np.nan
            continue
        present = counts[m] > 0
        km = int(present.sum())
        cnt = counts[m][present]
        cen = centroids[m][present]
        if criterion == "calinski_harabasz":
            wss = float(s2[m][present].sum())
            mean = sums[m][present].sum(axis=0) / n
            bss = float(np.sum(cnt * np.sum((cen - mean) ** 2, axis=1)))
            out[m] = (1.0 if wss == 0.0
                      else bss * (n - km) / (wss * (km - 1)))
        else:                                    # davies_bouldin
            scatter = s1[m][present] / np.maximum(cnt, 1.0)
            cd = np.sqrt(np.maximum(np.asarray(pairwise_sq_dists(
                jnp.asarray(cen, jnp.float32),
                jnp.asarray(cen, jnp.float32), mode="direct"),
                np.float64), 0.0))
            ratio = (scatter[:, None] + scatter[None, :]) \
                / np.where(cd > 0, cd, np.inf)
            np.fill_diagonal(ratio, 0.0)
            out[m] = float(np.mean(ratio.max(axis=1)))
    return out


# --------------------------------------------------------- external metrics
# Label-agreement scores against a ground truth (sklearn's external
# cluster-validity family).  These are O(n) contingency-table reductions —
# host NumPy is the right engine (no MXU work exists); they complete the
# metrics surface so a reference user migrating an evaluation pipeline
# finds the standard scores in one place.


def _contingency(labels_true, labels_pred):
    lt = np.asarray(labels_true).ravel()
    lp = np.asarray(labels_pred).ravel()
    if lt.shape != lp.shape:
        raise ValueError(f"label arrays differ in length: {lt.shape} vs "
                         f"{lp.shape}")
    if lt.size == 0:
        raise ValueError("label arrays must be non-empty")
    # Float label arrays (e.g. loadtxt output with NaN for missing rows)
    # must not cluster NaN as a real category (sklearn raises too).
    for arr in (lt, lp):
        if np.issubdtype(arr.dtype, np.floating):
            check_finite_array(arr, "labels contain NaN or Inf values")
    _, ti = np.unique(lt, return_inverse=True)
    _, pi = np.unique(lp, return_inverse=True)
    rows, cols = int(ti.max()) + 1, int(pi.max()) + 1
    return np.bincount(ti * cols + pi,
                       minlength=rows * cols).reshape(rows, cols)


def adjusted_rand_score(labels_true, labels_pred) -> float:
    """Adjusted Rand index (Hubert & Arabie) — chance-corrected pair
    agreement; 1.0 = identical partitions, ~0 = random."""
    c = _contingency(labels_true, labels_pred)
    n = c.sum()

    def comb2(x):
        return x * (x - 1) / 2.0

    sum_ij = comb2(c.astype(np.float64)).sum()
    a = comb2(c.sum(axis=1).astype(np.float64)).sum()
    b = comb2(c.sum(axis=0).astype(np.float64)).sum()
    expected = a * b / max(comb2(float(n)), 1.0)
    max_index = 0.5 * (a + b)
    if max_index == expected:          # degenerate: single cluster both
        return 1.0
    return float((sum_ij - expected) / (max_index - expected))


def _entropy(counts) -> float:
    p = counts[counts > 0].astype(np.float64)
    p = p / p.sum()
    return float(-(p * np.log(p)).sum())


def _mi_from_contingency(c) -> float:
    c = c.astype(np.float64)
    n = c.sum()
    outer = np.outer(c.sum(axis=1), c.sum(axis=0))
    nz = c > 0
    return float((c[nz] / n * (np.log(c[nz] * n) -
                               np.log(outer[nz]))).sum())


def mutual_info_score(labels_true, labels_pred) -> float:
    """Mutual information of the two partitions (nats)."""
    return _mi_from_contingency(_contingency(labels_true, labels_pred))


def normalized_mutual_info_score(labels_true, labels_pred) -> float:
    """NMI with arithmetic-mean normalization (sklearn's default)."""
    c = _contingency(labels_true, labels_pred)
    mi = _mi_from_contingency(c)
    h1 = _entropy(c.sum(axis=1))
    h2 = _entropy(c.sum(axis=0))
    denom = 0.5 * (h1 + h2)
    if denom == 0.0:                   # both partitions trivial
        return 1.0
    return float(np.clip(mi / denom, 0.0, 1.0))


def homogeneity_completeness_v_measure(labels_true, labels_pred):
    """(homogeneity, completeness, v-measure) — sklearn's definitions."""
    c = _contingency(labels_true, labels_pred)
    mi = _mi_from_contingency(c)
    h_true = _entropy(c.sum(axis=1))
    h_pred = _entropy(c.sum(axis=0))
    hom = 1.0 if h_true == 0.0 else mi / h_true
    com = 1.0 if h_pred == 0.0 else mi / h_pred
    v = (0.0 if hom + com == 0.0
         else 2.0 * hom * com / (hom + com))
    return float(hom), float(com), float(v)
