"""``python -m kmeans_tpu fit`` — cluster an on-disk matrix from the shell.

The reference has no CLI at all (SURVEY.md §1: its ``__main__`` takes no
arguments); this is a superset utility: point it at a ``.npy`` (or ``.npz``
key) of shape (n, D), get centroids/labels/summary artifacts back.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

_MODELS = ("kmeans", "minibatch", "bisecting", "spherical")


def _load_matrix(path: str, npz_key: str) -> np.ndarray:
    p = Path(path)
    if not p.exists():
        raise FileNotFoundError(f"no such file: {p}")
    if p.suffix == ".npz":
        with np.load(p) as z:
            keys = list(z.keys())
            if not keys:
                raise ValueError(f"{p} contains no arrays")
            key = npz_key or keys[0]
            if key not in keys:
                raise KeyError(f"{p} has no array {key!r}; "
                               f"available: {keys}")
            return np.asarray(z[key])
    return np.load(p)


def _parse_bucket(raw):
    """The ``--bucket`` grammar shared by ``fit`` and ``warm``:
    'auto' | an int >= 0 (0 = exact shape) — validated by the same
    ``check_bucket`` the model constructors use."""
    from kmeans_tpu.parallel.sharding import check_bucket
    return check_bucket(raw if raw == "auto" else int(raw))


def _build_model(args):
    from kmeans_tpu import (BisectingKMeans, KMeans, MiniBatchKMeans,
                            SphericalKMeans)
    common = dict(k=args.k, max_iter=args.max_iter, tolerance=args.tolerance,
                  seed=args.seed, compute_sse=args.sse, init=args.init,
                  n_init=args.n_init, verbose=not args.quiet,
                  bucket=_parse_bucket(getattr(args, "bucket", 0)))
    if args.model == "minibatch":
        # n_init > 1 selects the best-scoring candidate init
        # (sklearn-style), then runs one training session.
        return MiniBatchKMeans(batch_size=args.batch_size, **common)
    if args.model == "bisecting":
        return BisectingKMeans(**common)      # n_init applies per split
    if args.model == "spherical":
        return SphericalKMeans(**common)
    return KMeans(**common)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu fit",
        description="Cluster an (n, D) .npy/.npz matrix on TPU/CPU devices")
    parser.add_argument("data", help="path to .npy or .npz with (n, D) floats")
    parser.add_argument("--npz-key", default="",
                        help=".npz array name (default: first key)")
    parser.add_argument("--k", type=int, required=True)
    parser.add_argument("--model", choices=_MODELS, default="kmeans")
    parser.add_argument("--max-iter", type=int, default=100)
    parser.add_argument("--tolerance", type=float, default=1e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--init", default="forgy",
                        help="forgy | kmeans++ | kmeans|| (default forgy)")
    parser.add_argument("--n-init", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=4096,
                        help="minibatch model only")
    parser.add_argument("--sse", action="store_true",
                        help="track SSE per iteration")
    parser.add_argument("--bucket", default="0",
                        help="fit-shape bucket: 0 (exact, default) | "
                             "auto (committed ladder) | int step "
                             "(ISSUE 15: warm fleets reuse one "
                             "compiled program across nearby sizes)")
    parser.add_argument("--aot-cache", default=None, metavar="DIR",
                        help="AOT executable cache directory (also via "
                             "KMEANS_TPU_AOT_CACHE): serialized "
                             "compiled programs load here instead of "
                             "trace+compile on later runs")
    parser.add_argument("--out-dir", default=".",
                        help="where centroids.npy/labels.npy/summary.json go")
    parser.add_argument("--no-labels", action="store_true",
                        help="skip writing per-point labels")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    try:
        X = _load_matrix(args.data, args.npz_key)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if X.ndim != 2:
        print(f"error: expected (n, D) matrix, got shape {X.shape}",
              file=sys.stderr)
        return 2
    # First rung of the warm-start ladder (ISSUE 15 satellite): the
    # persistent compilation cache is library-level now — every CLI fit
    # gets it, not just bench runs (KMEANS_TPU_COMPILE_CACHE="" opts
    # out).
    from kmeans_tpu.utils import aot
    aot.enable_compilation_cache()
    if args.aot_cache:
        aot.configure(args.aot_cache)
    try:
        args.bucket = _parse_bucket(args.bucket)
    except ValueError:
        print(f"error: --bucket must be 'auto' or an int, got "
              f"{args.bucket!r}", file=sys.stderr)
        return 2
    model = _build_model(args)

    X = np.asarray(X, dtype=np.float32)
    start = time.perf_counter()
    model.fit(X)
    elapsed = time.perf_counter() - start
    # Real final inertia even without --sse (one fused pass).
    inertia = model.inertia_ if model.inertia_ is not None \
        else -model.score(X)

    out = Path(args.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.save(out / "centroids.npy", model.centroids)
    if not args.no_labels:
        np.save(out / "labels.npy", model.labels_)
    summary = {
        "model": args.model, "n": int(X.shape[0]), "d": int(X.shape[1]),
        "k": args.k, "iterations": model.iterations_run,
        "fit_seconds": round(elapsed, 3),
        "inertia": float(inertia),
        "sse_history": [float(s) for s in model.sse_history],
        "cluster_sizes": [int(c) for c in model.cluster_sizes_]
        if model.cluster_sizes_ is not None else None,
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    if not args.quiet:
        print(json.dumps(summary))
    return 0


def sweep_main(argv=None) -> int:
    """``python -m kmeans_tpu sweep`` — batched model selection over k
    (ISSUE 7): one vmapped device dispatch fits every (k, restart)
    member of the range, the criterion curve is scored in O(1) further
    dispatches, and the selected model's artifacts land in
    ``--out-dir``.

    ``--k-range`` uses the half-open grammar ``lo:hi[:step]`` (``2:33``
    is k ∈ {2..32}) or a comma list (``2,4,8``); an invalid or empty
    range exits 2.  ``--criterion`` defaults to the family's standard
    rule: ``inertia`` (elbow) for kmeans/spherical, ``bic`` for gmm.
    ``--sequential`` runs the per-member oracle instead (the parity
    reference; k_max·n_init separate fits).  ``--json`` prints the
    machine-readable summary (selected k, per-k scores, dispatch
    count) on stdout."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu sweep",
        description="Batched fit-many/pick-best model selection over a "
                    "k range — one device dispatch for the whole sweep")
    parser.add_argument("data", help="path to .npy or .npz with (n, D) "
                        "floats")
    parser.add_argument("--npz-key", default="",
                        help=".npz array name (default: first key)")
    parser.add_argument("--model", choices=("kmeans", "spherical", "gmm"),
                        default="kmeans")
    parser.add_argument("--k-range", required=True,
                        help="half-open 'lo:hi[:step]' (2:33 = k 2..32) "
                             "or comma list '2,4,8'")
    parser.add_argument("--criterion", default=None,
                        help="kmeans/spherical: inertia | silhouette | "
                             "calinski_harabasz | davies_bouldin; "
                             "gmm: bic | aic (default: inertia / bic)")
    parser.add_argument("--n-init", type=int, default=1,
                        help="restarts per k (default 1)")
    parser.add_argument("--max-iter", type=int, default=100)
    parser.add_argument("--tolerance", type=float, default=1e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--init", default="forgy",
                        help="kmeans family init (default forgy)")
    parser.add_argument("--cov-type", default="diag",
                        choices=("diag", "spherical", "tied", "full"),
                        help="gmm only (batched sweep needs "
                             "diag/spherical)")
    parser.add_argument("--sequential", action="store_true",
                        help="run the per-member oracle (batched=0)")
    parser.add_argument("--out-dir", default=".",
                        help="where centroids.npy/sweep.json go")
    parser.add_argument("--json", action="store_true",
                        help="print the JSON summary on stdout")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    from kmeans_tpu.sweep import (GMM_CRITERIA, KMEANS_CRITERIA,
                                  parse_k_range)
    try:
        ks = parse_k_range(args.k_range)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    criterion = args.criterion or ("bic" if args.model == "gmm"
                                   else "inertia")
    table = GMM_CRITERIA if args.model == "gmm" else KMEANS_CRITERIA
    if criterion not in table:
        print(f"error: criterion {criterion!r} is not valid for "
              f"--model {args.model} (valid: {sorted(table)})",
              file=sys.stderr)
        return 2
    try:
        X = _load_matrix(args.data, args.npz_key)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if X.ndim != 2:
        print(f"error: expected (n, D) matrix, got shape {X.shape}",
              file=sys.stderr)
        return 2
    if ks[-1] >= X.shape[0]:
        print(f"error: k range max {ks[-1]} must be < n={X.shape[0]}",
              file=sys.stderr)
        return 2

    X = np.asarray(X, dtype=np.float32)
    if args.model == "gmm":
        from kmeans_tpu import GaussianMixture
        model = GaussianMixture(
            n_components=ks[-1], covariance_type=args.cov_type,
            max_iter=args.max_iter, tol=args.tolerance, seed=args.seed,
            n_init=args.n_init, verbose=False)
    else:
        from kmeans_tpu import KMeans, SphericalKMeans
        cls = SphericalKMeans if args.model == "spherical" else KMeans
        model = cls(k=ks[-1], max_iter=args.max_iter,
                    tolerance=args.tolerance, seed=args.seed,
                    init=args.init, n_init=args.n_init, verbose=False)

    start = time.perf_counter()
    try:
        result = model.sweep(X, k_range=ks, criterion=criterion,
                             batched=0 if args.sequential else True)
    except ValueError as e:
        # sweep() validates deeper than the pre-checks above can (e.g.
        # metric criteria need k >= 2, every member non-finite) — same
        # 'error: ... exit 2' contract as the argument failures.
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - start

    summary = result.summary()
    summary.update({"model": args.model, "n": int(X.shape[0]),
                    "d": int(X.shape[1]),
                    "sweep_seconds": round(elapsed, 3)})
    out = Path(args.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    best = result.best_model
    np.save(out / "centroids.npy",
            best.centroids if args.model != "gmm" else best.means_)
    (out / "sweep.json").write_text(json.dumps(summary, indent=2))
    if args.json:
        print(json.dumps(summary))
    elif not args.quiet:
        curve = "  ".join(f"k={k}:{summary['scores'][str(k)]:.4g}"
                          if summary["scores"][str(k)] is not None
                          else f"k={k}:-" for k in result.k_range)
        print(f"sweep: selected k={result.selected_k} by {criterion} "
              f"({result.n_dispatches} dispatches, {elapsed:.2f}s)\n"
              f"  {curve}")
    return 0


def serve_main(argv=None) -> int:
    """``python -m kmeans_tpu serve --model <ckpt> [--model <ckpt> ...]``
    — stdin/JSONL request loop over the serving engine (ISSUE 6; no
    network dependency — pipe requests in, read results out).

    Protocol: one JSON object per input line.

    * ``{"model": "<id>", "x": [[...], ...]}`` — label the rows;
      optional ``"op"``: ``predict`` (default) | ``transform`` |
      ``score_rows`` | ``predict_proba`` | ``score_samples`` (family
      permitting), optional ``"id"`` echoed back.  Reply line:
      ``{"model":..., "op":..., "result": [...]}``.  With a single
      resident model ``"model"`` may be omitted.
    * ``{"stats": true}`` — reply with the engine stats snapshot
      (models resident, dispatches, batch-fill histogram).
    * ``{"quality": true}`` — reply with the per-model drift-monitor
      snapshot (ISSUE 14: detector readings, debounced state, event
      counts; ``--quality-dir`` additionally streams the per-model
      JSONL sinks ``serve-status`` reads).
    * ``{"learn": true}`` — with ``--learn`` (ISSUE 20 serve-and-learn
      actuator), reply with the per-model update status (armed state,
      budgets left, reservoir fill, pending evaluation, recent
      decision log; per replica in fleet mode); an error line when
      serving without ``--learn``.
    * ``{"fleet_stats": true}`` — with ``--replicas N`` (ISSUE 17:
      in-process :class:`ServingFleet` — N replica engines behind the
      SLO-aware router), reply with the fleet snapshot (per-replica
      liveness/load, placement, route/shed counters); an error line
      under a single engine.  ``--quality-dir`` doubles as the fleet
      directory (per-replica quality + heartbeat sinks — the
      ``serve-status``/``fleet-status`` input), and ``--slo-p99-ms``
      commits the admission bound (shed requests error THEIR line,
      explicitly).

    A malformed/poisoned request errors ITS line
    (``{"error": ...}``) and the loop keeps serving.  On EOF the
    engine drains; ``--json`` prints a final stats line to stdout
    (``ckpt-info --json`` style), otherwise a human summary goes to
    stderr.  Exit 0 after a clean drain, 2 when no model loaded."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu serve",
        description="Serve fitted-model checkpoints over a stdin/JSONL "
                    "request loop (resident warm-kernel engine; each "
                    "line dispatches immediately — the pipe is serial)")
    parser.add_argument("--model", action="append", required=True,
                        metavar="CKPT", dest="models",
                        help="checkpoint path (repeatable; any family)")
    parser.add_argument("--id", action="append", default=None,
                        dest="ids", help="model id for the matching "
                        "--model (default: file stem)")
    parser.add_argument("--quantize", choices=["bf16"], default=None,
                        help="serve K-Means-family assignment through "
                             "the bf16 distance fast path")
    parser.add_argument("--max-wait-ms", type=float, default=2.0,
                        help="micro-batch flush timer for the engine's "
                             "queue (default 2.0; the serial stdin loop "
                             "itself dispatches immediately)")
    parser.add_argument("--buckets", default="8,64,512,4096",
                        help="request-batch bucket ladder")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip pre-compiling the bucket shapes")
    parser.add_argument("--replicas", type=int, default=1, metavar="N",
                        help="serve through an in-process fleet of N "
                             "replica engines behind the SLO-aware "
                             "router (default 1: a single engine)")
    parser.add_argument("--slo-p99-ms", type=float, default=None,
                        metavar="MS",
                        help="committed p99 latency bound for fleet "
                             "admission control (requests shed at the "
                             "bound error their line explicitly; "
                             "requires --replicas >= 1 fleet mode)")
    parser.add_argument("--quality-dir", default=None, metavar="DIR",
                        help="write per-model drift JSONL sinks "
                             "(quality.<id>.jsonl) here — the "
                             "serve-status input; implies monitoring "
                             "on")
    parser.add_argument("--quality", action="store_true",
                        help="force drift monitoring on (default "
                             "'auto': on on accelerators, off on CPU "
                             "— the measured BENCH_QUALITY rule)")
    parser.add_argument("--no-quality", action="store_true",
                        help="disable drift monitoring (the blind "
                             "r11 engine)")
    parser.add_argument("--learn", action="store_true",
                        help="serve-and-learn (ISSUE 20): let eligible "
                             "resident models update in place from "
                             "sampled traffic when their drift monitor "
                             "fires — snapshot-before-update, atomic "
                             "swap, rollback-on-regression; implies "
                             "quality monitoring on")
    parser.add_argument("--json", action="store_true",
                        help="print the final stats snapshot as JSON "
                             "on stdout")
    args = parser.parse_args(argv)

    from kmeans_tpu.serving import ServingEngine, ServingFleet
    ids = args.ids or []
    if len(ids) > len(args.models):
        print("error: more --id flags than --model flags",
              file=sys.stderr)
        return 2
    if args.replicas < 1:
        print("error: --replicas must be >= 1", file=sys.stderr)
        return 2
    buckets = tuple(int(b) for b in args.buckets.split(","))
    if args.quality and args.no_quality:
        print("error: --quality and --no-quality are mutually "
              "exclusive", file=sys.stderr)
        return 2
    quality = (False if args.no_quality else True if args.quality
               else "auto")
    if args.learn:
        if args.no_quality:
            print("error: --learn requires quality monitoring (the "
                  "serve-and-learn trigger IS the drift monitor); "
                  "drop --no-quality", file=sys.stderr)
            return 2
        # The learn trigger is the drift monitor, so 'auto' must not
        # resolve quality off on CPU under --learn.
        if quality == "auto":
            quality = True
    fleet_mode = args.replicas > 1 or args.slo_p99_ms is not None
    if fleet_mode:
        engine = ServingFleet(
            args.replicas, buckets=buckets,
            max_wait_ms=args.max_wait_ms, quality=quality,
            fleet_dir=(None if args.no_quality else args.quality_dir),
            slo_p99_ms=args.slo_p99_ms, learn=args.learn)
        print(f"serve: fleet of {args.replicas} replicas"
              + (f", SLO p99 <= {args.slo_p99_ms} ms"
                 if args.slo_p99_ms is not None else ""),
              file=sys.stderr)
    else:
        engine = ServingEngine(buckets=buckets,
                               max_wait_ms=args.max_wait_ms,
                               quality=quality,
                               quality_dir=(None if args.no_quality
                                            else args.quality_dir),
                               learn=args.learn)
    try:
        for i, path in enumerate(args.models):
            mid = ids[i] if i < len(ids) else None
            try:
                mid = engine.load(path, mid, quantize=args.quantize)
            except Exception as e:       # noqa: BLE001 — operator-facing
                print(f"error: cannot load {path}: {e}", file=sys.stderr)
                return 2
            spec = engine.registry.spec(mid)
            print(f"serve: resident {mid!r}: {spec['model_class']} "
                  f"k={spec['k']} d={spec['d']} dtype={spec['dtype']}"
                  + (f" quantize={args.quantize}" if args.quantize
                     and spec["family"] == "kmeans" else ""),
                  file=sys.stderr)
        if not args.no_warmup:
            n = engine.warmup()
            print(f"serve: warmed {n} bucket shapes", file=sys.stderr)
        elif fleet_mode:
            # Replicas take traffic only in state 'serving': open the
            # fleet without pre-compiling (the --no-warmup contract).
            engine.warmup(prewarm=False)
        default_model = engine.models()[0] \
            if len(engine.models()) == 1 else None

        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                if req.get("stats"):
                    print(json.dumps(engine.stats()), flush=True)
                    continue
                if req.get("quality"):
                    print(json.dumps(engine.quality_status()),
                          flush=True)
                    continue
                if req.get("learn"):
                    if not args.learn:
                        raise ValueError(
                            "learn status requires serving with "
                            "--learn (the serve-and-learn actuator "
                            "is off)")
                    print(json.dumps(engine.update_status()),
                          flush=True)
                    continue
                if req.get("fleet_stats"):
                    if not fleet_mode:
                        raise ValueError(
                            "fleet_stats requires --replicas N fleet "
                            "mode (a single engine has no fleet)")
                    print(json.dumps(engine.stats()), flush=True)
                    continue
                model_id = req.get("model", default_model)
                if model_id is None:
                    raise ValueError(
                        "request must name a 'model' (several are "
                        "resident)")
                op = req.get("op", "predict")
                # The stdin loop is strictly serial (each reply is
                # written before the next line is read), so queueing
                # could never coalesce anything — it would only add the
                # max_wait_ms flush-timer wait per request.  Dispatch
                # immediately.
                result = engine.call(model_id, req["x"], op=op)
                reply = {"model": model_id, "op": op,
                         "result": np.asarray(result).tolist()}
                if "id" in req:
                    reply["id"] = req["id"]
                print(json.dumps(reply), flush=True)
            except Exception as e:       # noqa: BLE001 — per-request
                print(json.dumps({"error": f"{type(e).__name__}: {e}"}),
                      flush=True)
    finally:
        engine.close()
    if args.json:
        print(json.dumps(engine.stats()))
    else:
        st = engine.stats()
        n_req = sum(m["requests"] for m in st["models"].values())
        n_models = st.get("models_resident", len(st["models"]))
        print(f"serve: done — {n_models} models, "
              f"{n_req} requests, "
              f"{st['dispatches']} dispatches"
              + (f" across {st['n_replicas']} replicas "
                 f"({st['routes']} routed, {st['sheds']} shed)"
                 if fleet_mode else ""), file=sys.stderr)
    return 0


def trace_main(argv=None) -> int:
    """``python -m kmeans_tpu trace summarize <file.jsonl> [...]`` —
    analyze telemetry traces written by ``obs.tracing(path=...)``
    (ISSUE 11; fleet merge ISSUE 13).

    One file: per-phase rollup (count / total / p50 / p99 over SELF
    time — nested child time is excluded, so totals never double-count)
    and, when the trace holds a ``dispatch`` span, the
    time-to-first-iteration table (the same ``phase_ceiling_table``
    schema as the r13 per-iteration ceiling table, with the committed
    >= 15% "actionable" rule).

    Several files (or a directory / glob — the per-host
    ``trace.p{idx}.jsonl`` family ``obs.tracing`` writes under
    ``process_count > 1``): the streams are clock-aligned and MERGED
    first (``obs.fleet.merge_traces`` — barrier-anchored when synced
    fleet barriers exist, wall-anchored otherwise), the host roster +
    measured skew bound print above the rollup, and the TTFI table is
    per-reference-host territory so it is omitted.  ``--json`` emits
    everything machine-readable; ``--chrome out.json`` converts to
    Chrome ``trace_event`` (merged: one track group per host).  Exit 2
    on unreadable, malformed, or clock-unalignable inputs
    (``TraceReadError`` classification, the single-file contract
    extended)."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu trace",
        description="Summarize kmeans_tpu telemetry traces (JSONL from "
                    "obs.tracing): per-phase totals/percentiles + the "
                    "time-to-first-iteration table; several files / a "
                    "directory merge into one fleet timeline")
    parser.add_argument("action", choices=("summarize",),
                        help="analysis to run (summarize)")
    parser.add_argument("file", nargs="+",
                        help="trace JSONL path(s), a directory, or a "
                             "glob (per-host trace.p{idx}.jsonl files)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output on stdout")
    parser.add_argument("--chrome", metavar="OUT.JSON", default=None,
                        help="also write a Chrome trace_event file")
    parser.add_argument("--cost", action="store_true",
                        help="merge captured CostRecords (ISSUE 12: "
                             "cost.record events from obs.cost capture) "
                             "into the per-phase table — flops/bytes/"
                             "arithmetic-intensity columns, blank when "
                             "the trace holds no cost records (no "
                             "trace-derived MFU: captured flops count "
                             "one loop-body pass, a span may cover "
                             "many)")
    args = parser.parse_args(argv)

    from kmeans_tpu.obs import fleet as obs_fleet
    from kmeans_tpu.obs import trace as obs_trace
    from kmeans_tpu.obs.report import (format_ingest_table,
                                       format_phase_table, ingest_breakdown,
                                       merge_cost, time_to_first_iteration)
    merged = None
    try:
        paths = obs_fleet.expand_fleet_paths(args.file)
        if len(paths) > 1:
            # Directory/glob/multi-file inputs naturally co-locate
            # heartbeat sinks next to the trace sinks — skip the
            # heartbeat streams instead of failing the merge on them
            # (one explicitly-named file stays strict: reading it as a
            # trace is what the user asked for).
            trace_paths = [p for p in paths
                           if obs_fleet.sniff_stream(p)
                           != "heartbeat"]
            if not trace_paths:
                raise obs_trace.TraceReadError(
                    f"no trace streams among {paths} (heartbeat files "
                    f"are read by 'fleet-status')")
            paths = trace_paths
        if len(paths) == 1:
            records = obs_trace.read_jsonl(paths[0])
        else:
            merged = obs_fleet.merge_traces(paths)
            records = merged["records"]
    except obs_trace.TraceReadError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    summary = obs_trace.summarize(records)
    ttfi = None
    if merged is None:
        try:
            ttfi = time_to_first_iteration(records)
        except ValueError:
            ttfi = None              # no dispatch span — summary only
    cost = merge_cost(records) if args.cost else None
    # Per-slab ingest attribution (ISSUE 18): present whenever the
    # trace carries slab-staged 'stage' spans (single-file AND merged
    # fleet traces — placement is per-host work either way).
    slabs = ingest_breakdown(records)

    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": obs_trace.chrome_events(records),
                       "displayTimeUnit": "ms"}, f)

    if args.json:
        from kmeans_tpu.utils.profiling import sanitize_json
        out = {"files": paths, "phases": summary,
               "time_to_first_iteration": ttfi,
               "ingest_slabs": slabs or None,
               "chrome": args.chrome}
        if merged is not None:
            out["fleet"] = {k: merged[k] for k in
                            ("hosts", "align", "barriers",
                             "skew_bound_s", "ntp_delta_s")}
        if args.cost:
            out["cost"] = cost
        print(json.dumps(sanitize_json(out), indent=2))
        return 0

    n_spans = sum(1 for r in records if r.get("kind") == "span")
    n_events = sum(1 for r in records if r.get("kind") == "event")
    if merged is not None:
        print(obs_fleet.format_fleet_summary(merged))
        print()
    print(f"trace: {', '.join(paths)} — {n_spans} spans, "
          f"{n_events} events")
    header = (f"  {'phase':<20} {'count':>6} {'total ms':>10} "
              f"{'p50 ms':>9} {'p99 ms':>9} {'events':>7}")
    if args.cost:
        # flops/bytes/AI of the captured programs.  Deliberately NO
        # wall-time MFU here: captured flops count ONE loop-body pass
        # (the obs.cost convention) while a span may cover many
        # iterations/chunks, so any trace-derived MFU would understate
        # by that multiplicity.  AI is per-pass on both sides and
        # therefore sound; analytic MFU lives where a measured
        # per-iteration marginal exists (phase_ceiling_table / the
        # BENCH_COST rows).
        header += f" {'flops':>10} {'bytes':>10} {'ai':>7}"
    print(header)
    for name in sorted(summary,
                       key=lambda n: -summary[n]["total"]):
        row = summary[name]
        line = (f"  {name:<20} {row['count']:>6} "
                f"{row['total'] * 1e3:>10.2f} {row['p50'] * 1e3:>9.3f} "
                f"{row['p99'] * 1e3:>9.3f} {row['events']:>7}")
        if args.cost:
            c = (cost or {}).get(name)
            if c and c["programs"]:
                ai = c.get("ai")
                line += (f" {c['flops']:>10.3g} "
                         f"{c['bytes_accessed']:>10.3g} "
                         + (f"{ai:>7.2f}" if ai is not None
                            else f"{'-':>7}"))
            else:
                line += f" {'-':>10} {'-':>10} {'-':>7}"
        print(line)
    if ttfi is not None:
        print()
        print(format_phase_table(ttfi))
    if slabs:
        print()
        print(format_ingest_table(slabs))
    if args.chrome:
        print(f"\nchrome trace written to {args.chrome} "
              f"(load in chrome://tracing or ui.perfetto.dev)")
    return 0


def fleet_status_main(argv=None) -> int:
    """``python -m kmeans_tpu fleet-status <dir-or-files> [--json]`` —
    per-host progress/liveness/lag from merged heartbeat streams
    (ISSUE 13): the table ROADMAP item 1's elastic orchestration loop
    consumes.

    Inputs: heartbeat JSONL files (the per-process ``hb.p{idx}.jsonl``
    family ``obs.heartbeat`` writes), a directory, or a glob; trace
    files found alongside are ignored here (``trace summarize`` reads
    those).  The report applies the committed straggler rules
    (``obs.fleet``: rows/s below ``rate_factor`` x the fleet median ->
    ``slow``; trailing the leader by >= ``behind_iters`` iterations ->
    ``behind``; silent past the stall window while behind ->
    ``stalled``).  ``--now`` anchors liveness at the current wall
    clock (live monitoring) instead of the newest record (post-hoc) —
    and tightens the stall rule: a host silent past the window whose
    last beat is mid-fit (not a terminal completion beat) flags
    ``stalled`` even at the leader iteration, so a live-but-paused
    fleet never reads healthy (ISSUE 19 fix).

    Exit 0: healthy fleet.  Exit 1: stragglers flagged (the
    orchestrator's signal).  Exit 2: unreadable/malformed inputs or no
    heartbeat records."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu fleet-status",
        description="Per-host progress/liveness/lag table from merged "
                    "fleet heartbeat files")
    parser.add_argument("paths", nargs="+",
                        help="heartbeat JSONL file(s), directory, or "
                             "glob")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--now", action="store_true",
                        help="anchor liveness at the current wall "
                             "clock (live monitoring) instead of the "
                             "newest record (post-hoc)")
    parser.add_argument("--rate-factor", type=float, default=None,
                        help="override the committed slow-host rows/s "
                             "factor")
    parser.add_argument("--behind-iters", type=int, default=None,
                        help="override the committed behind-leader "
                             "iteration threshold")
    args = parser.parse_args(argv)

    from kmeans_tpu.obs import fleet as obs_fleet
    from kmeans_tpu.obs.trace import TraceReadError
    try:
        files = obs_fleet.expand_fleet_paths(args.paths)
        hb_files = [p for p in files
                    if obs_fleet.sniff_stream(p) != "trace"]
        if not hb_files:
            raise TraceReadError(
                f"no heartbeat files among {files} (trace streams are "
                f"summarized by 'trace summarize')")
        records = obs_fleet.merge_heartbeats(hb_files)
        kwargs = {}
        if args.now:
            kwargs["now"] = time.time()
        if args.rate_factor is not None:
            kwargs["rate_factor"] = args.rate_factor
        if args.behind_iters is not None:
            kwargs["behind_iters"] = args.behind_iters
        report = obs_fleet.straggler_report(records, **kwargs)
    except TraceReadError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        from kmeans_tpu.utils.profiling import sanitize_json
        print(json.dumps(sanitize_json({"files": hb_files, **report})))
    else:
        print(obs_fleet.format_fleet_status(report))
    return 0 if report["healthy"] else 1


def autopilot_main(argv=None) -> int:
    """``python -m kmeans_tpu autopilot --spec spec.json --out dir
    --world N [--json]`` — supervise a fleet of per-host ``fit(resume=)``
    workers under the committed elastic rules (ISSUE 19,
    ``orchestrator.policy``): relaunch the preempted from the last
    rotating checkpoint, evict stalled hosts and shrink, grow back when
    capacity returns, back off deterministically on launch flakes, and
    refuse with the full typed decision log when a committed budget is
    exhausted.  See docs/AUTOPILOT.md for the spec schema and the
    decision-rule table.

    Exit 0: converged at the target world.  Exit 1: finished but
    degraded (a shrunk fleet completed the fit).  Exit 2: gave up
    (``AutopilotGaveUpError`` — decision log on stderr) or bad
    inputs."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu autopilot",
        description="Elastic supervising loop over per-host fit "
                    "workers (evict/shrink/grow/relaunch under "
                    "committed typed rules)")
    parser.add_argument("--spec", required=True,
                        help="worker spec JSON (docs/AUTOPILOT.md)")
    parser.add_argument("--out", required=True,
                        help="run directory (checkpoints, heartbeats, "
                             "decision log, worker artifacts)")
    parser.add_argument("--world", type=int, required=True,
                        help="fleet size to launch")
    parser.add_argument("--target-world", type=int, default=None,
                        help="world size that counts as converged "
                             "(default: --world)")
    parser.add_argument("--no-grow", action="store_true",
                        help="never grow a shrunk fleet back")
    parser.add_argument("--poll-period", type=float, default=None,
                        help="override the committed poll period "
                             "(seconds)")
    parser.add_argument("--max-run-s", type=float, default=None,
                        help="override the committed run deadline")
    parser.add_argument("--coordinator-address", default=None,
                        help="real jax.distributed coordinator "
                             "(default: simulated fleet env)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable result on stdout")
    args = parser.parse_args(argv)

    from kmeans_tpu.orchestrator import Autopilot, AutopilotGaveUpError
    kwargs = {}
    if args.target_world is not None:
        kwargs["target_world"] = args.target_world
    if args.poll_period is not None:
        kwargs["poll_period_s"] = args.poll_period
    if args.max_run_s is not None:
        kwargs["max_run_s"] = args.max_run_s
    if args.coordinator_address is not None:
        kwargs["coordinator_address"] = args.coordinator_address
    try:
        pilot = Autopilot(args.spec, args.out, args.world,
                          grow=not args.no_grow, **kwargs)
        result = pilot.run()
    except AutopilotGaveUpError as e:
        # Routed fault path: the typed give-up maps to the committed
        # exit code 2, decision log rendered for the operator.
        if args.json:
            from kmeans_tpu.utils.profiling import sanitize_json
            print(json.dumps(sanitize_json(
                {"outcome": "gave-up", "exit_code": 2,
                 "reason": e.reason,
                 "decisions": [d.as_dict() for d in e.decisions]})))
        print(e.report(), file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        from kmeans_tpu.utils.profiling import sanitize_json
        print(json.dumps(sanitize_json(result.as_dict())))
    else:
        print(f"autopilot {result.outcome}: fleet of "
              f"{result.final_world}/{result.target_world} finished, "
              f"{len(result.decisions)} decisions "
              f"(log: {result.out_dir}/autopilot.decisions.jsonl)")
    return result.exit_code


def cost_report_main(argv=None) -> int:
    """``python -m kmeans_tpu cost-report`` — device-cost observability
    report (ISSUE 12): run each model family's small fit under XLA
    cost/memory capture and print the per-program table — XLA-reported
    flops vs the analytic roofline formulas (ratio + the committed 10%
    agreement band), arithmetic intensity, XLA per-program peak bytes
    vs the HBM footprint planner's prediction — plus the per-device
    plan table and the device's free-memory snapshot (unreported on
    CPU).  ``--json`` emits the machine-readable payload; a backend
    that cannot report yields ``available=False`` rows, never a
    failure.  Exit 0 always when the fits themselves succeed."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu cost-report",
        description="XLA cost/memory analysis per compiled step "
                    "program: analytic-FLOPs cross-check, roofline, "
                    "and the HBM footprint plan")
    parser.add_argument("--families", default=None,
                        help="comma list (default: kmeans,spherical,"
                             "bisecting,minibatch,gmm)")
    parser.add_argument("--n", type=int, default=None,
                        help="rows override for every family")
    parser.add_argument("--d", type=int, default=None)
    parser.add_argument("--k", type=int, default=None)
    parser.add_argument("--chunk", type=int, default=None,
                        help="explicit scan chunk (default: one whole "
                             "shard, the analytic-agreement regime)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON on stdout")
    args = parser.parse_args(argv)

    from kmeans_tpu.obs.memory import FAMILIES, format_plan_table
    from kmeans_tpu.obs.report import (device_cost_report,
                                       format_cost_table)
    families = [f.strip() for f in args.families.split(",")] \
        if args.families else None
    for fam in families or ():
        if fam not in FAMILIES:
            print(f"error: unknown family {fam!r}; families: "
                  f"{','.join(FAMILIES)}", file=sys.stderr)
            return 2
    override = {k: v for k, v in
                (("n", args.n), ("d", args.d), ("k", args.k))
                if v is not None}
    specs = {fam: dict(override)
             for fam in (families or FAMILIES)} if override else None
    rep = device_cost_report(families, specs=specs, chunk=args.chunk)
    if args.json:
        from kmeans_tpu.utils.profiling import sanitize_json
        print(json.dumps(sanitize_json(rep), default=str))
        return 0
    print(format_cost_table(rep["rows"],
                            title=f"device cost ({rep['backend']})"))
    print()
    print(format_plan_table(rep["plans"]))
    return 0


def plan_main(argv=None) -> int:
    """``python -m kmeans_tpu plan --n N --d D --k K [...]`` — the r16
    HBM planner + the massive-k resolution (ISSUE 16), standalone: the
    dense per-device footprint at (N, D, k, mesh, chunk), the k-sharded
    footprint when the mesh has a TP axis, and the ``k_shard``/
    ``assign`` values the ``'auto'`` rule would pick on THIS backend —
    the same 80%-of-free-bytes rule ``KMeans._resolve_large_k``
    applies at fit time (kept in lockstep; the resolution text names
    which branch decided).  Pure arithmetic plus one allocator-stats
    read: no arrays are placed, so planning a 64k-centroid fit costs
    milliseconds."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu plan",
        description="Per-device HBM footprint plan + massive-k "
                    "k_shard/assign resolution for a fit shape")
    parser.add_argument("--n", type=int, required=True, help="rows")
    parser.add_argument("--d", type=int, required=True, help="features")
    parser.add_argument("--k", type=int, required=True, help="clusters")
    parser.add_argument("--data-shards", type=int, default=None,
                        help="default: local device count")
    parser.add_argument("--model-shards", type=int, default=1)
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--chunk", type=int, default=None,
                        help="scan chunk (default: the auto VMEM rule)")
    parser.add_argument("--k-shard", default="auto",
                        help="auto | 0 | <model_shards> (the KMeans "
                             "knob grammar)")
    parser.add_argument("--assign", default="auto",
                        choices=("auto", "dense", "two_level"))
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    from kmeans_tpu.obs import memory as _mem
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    import jax
    S = args.data_shards if args.data_shards is not None \
        else jax.local_device_count()
    M = int(args.model_shards)
    if args.k_shard != "auto":
        try:
            ks_req = int(args.k_shard)
        except ValueError:
            print(f"error: --k-shard must be 'auto' or an int, got "
                  f"{args.k_shard!r}", file=sys.stderr)
            return 2
        if ks_req not in (0, M) or (ks_req and M <= 1):
            print(f"error: --k-shard={ks_req} must be 0 or match "
                  f"--model-shards={M} (the table shards on the "
                  f"existing TP axis)", file=sys.stderr)
            return 2
    chunk = args.chunk or choose_chunk_size(
        -(-args.n // S), max(args.k, M), args.d)
    plans = [_mem.plan_fit("kmeans", args.n, args.d, args.k,
                           data_shards=S, model_shards=M,
                           dtype=args.dtype, chunk=chunk, k_shard=0)]
    if M > 1:
        plans.append(_mem.plan_fit("kmeans", args.n, args.d, args.k,
                                   data_shards=S, model_shards=M,
                                   dtype=args.dtype, chunk=chunk,
                                   k_shard=M))
    # The fit-time auto rule, mirrored (KMeans._resolve_large_k): the
    # DENSE plan against 80% of the device's free bytes; no allocator
    # stats (CPU) -> the bit-exact dense oracles.
    info = _mem.device_memory_info()
    fits = True
    if info.get("available"):
        fits = plans[0]["predicted_peak_bytes"] <= 0.8 * info["bytes_free"]
    ks = (0 if (fits or M <= 1) else M) if args.k_shard == "auto" \
        else int(args.k_shard)
    asg = ("dense" if (fits or M > 1) else "two_level") \
        if args.assign == "auto" else args.assign
    if asg == "two_level" and M != 1:
        print("error: assign='two_level' composes with data "
              "parallelism only (model_shards == 1); on a TP mesh use "
              "k_shard instead", file=sys.stderr)
        return 2
    why = ("allocator stats unavailable on this backend — dense "
           "oracles" if not info.get("available")
           else "dense plan fits in 80% of free HBM" if fits
           else "dense plan exceeds 80% of free HBM")
    resolution = {"k_shard": ks, "assign": asg,
                  "auto_rule": why,
                  "dense_predicted_peak_bytes":
                      plans[0]["predicted_peak_bytes"],
                  "device_memory": info}
    if args.json:
        from kmeans_tpu.utils.profiling import sanitize_json
        print(json.dumps(sanitize_json(
            {"plans": plans, "resolution": resolution}), default=str))
        return 0
    print(_mem.format_plan_table(
        plans, title=f"hbm footprint plan ({S}x{M} mesh)"))
    print()
    print(f"resolution     : k_shard={ks}, assign={asg!r}  [{why}]")
    if M > 1:
        dense, shard = plans[0], plans[1]
        saved = dense["predicted_peak_bytes"] \
            - shard["predicted_peak_bytes"]
        print(f"k-shard saves  : {saved:,} B/device of predicted peak "
              f"(replicated full-k stats accumulators -> local shard)")
    return 0


def serve_status_main(argv=None) -> int:
    """``python -m kmeans_tpu serve-status <dir-or-files> [--json]`` —
    per-model serving-quality/drift table from the quality JSONL sinks
    a monitored :class:`~kmeans_tpu.serving.ServingEngine` writes
    (``quality.<model_id>.jsonl`` under ``quality_dir`` / the serve
    CLI's ``--quality-dir``): the mirror of ``fleet-status`` for the
    serving half (ISSUE 14), and the trigger signal ROADMAP item 4's
    serve-and-learn loop consumes.

    The report applies the monitor's COMMITTED thresholds + debounce
    as recorded in the streams (``obs.drift``): a model is
    ``DRIFTING`` when its newest record's debounced state says so —
    PSI/JS assignment shift, rolling score-per-row ratio, or bf16
    near-tie fraction held over threshold for the debounce window.
    Trace/heartbeat files found alongside are skipped (``trace
    summarize`` / ``fleet-status`` read those).

    Exit 0: every monitored model healthy.  Exit 1: at least one model
    drifting.  Exit 2: unreadable/malformed inputs or no quality
    records."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu serve-status",
        description="Per-model serving-quality & drift table from a "
                    "monitored engine's quality JSONL sinks")
    parser.add_argument("paths", nargs="+",
                        help="quality JSONL file(s), directory, or "
                             "glob")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    args = parser.parse_args(argv)

    from kmeans_tpu.obs import drift as obs_drift
    from kmeans_tpu.obs.trace import TraceReadError
    try:
        report = obs_drift.quality_report(args.paths)
    except TraceReadError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    else:
        print(obs_drift.format_quality_status(report))
    return 0 if report["healthy"] else 1


#: bench-diff metric directions: which numeric row fields are
#: comparable, and which way is worse.  A field absent from both rows
#: is skipped; spread-style/meta fields are never compared.
_BENCH_LOWER_BETTER = ("ms_per_iter", "p50_ms", "p99_ms",
                       "overhead_x", "overhead_ratio",
                       "cpu_init_device_s", "batched_s", "resume_ms",
                       "save_ms",
                       # TTFI rows (ISSUE 15): span-table phase costs
                       # and the BENCH_TTFI cold/warm/AOT-warm rows —
                       # cold->warm regressions in time-to-first-
                       # iteration guard like ms/iter rows.
                       "ms", "ttfi_s", "compile_ms", "first_dispatch_ms",
                       "overlap_window_s",
                       # Serve-and-learn (ISSUE 20): the BENCH_LEARN
                       # p99 excursion ratio — growth means update
                       # work leaking into the dispatch path.
                       "excursion_ratio")
_BENCH_HIGHER_BETTER = ("value", "pts_dims_per_s_chip", "qps",
                        "speedup_vs_sequential", "overlap_speedup",
                        "step_mfu")
#: Regression allowance floor when a row recorded no spread (the
#: repo's publication bar: rows are published at <= 5% spread).
_BENCH_DEFAULT_SPREAD = 0.05


#: Fields that tell apart rows sharing one config/model key (e.g. the
#: per-batch-size serving rows) — tried in order before falling back
#: to the occurrence index (append-only artifacts keep occurrence
#: order stable, so old/new keys still align).
#: "k" discriminates the BENCH_LARGEK k-sweep rows (ISSUE 16: one row
#: per table size under a shared method label); "replicas" the
#: BENCH_FLEET 1->N scaling rows (ISSUE 17).
_BENCH_DISCRIMINATORS = ("batch_requests", "batch", "clients", "k",
                         "replicas", "ingest")


def _ttfi_trace_rows(records) -> list:
    """A trace JSONL artifact (``artifacts/trace_ttfi.jsonl``-class:
    span records from ``obs.tracing``) rendered as bench-diff rows —
    one ``ttfi <phase>`` row per phase with its ``ms`` (ISSUE 15
    satellite: cold->warm TTFI regressions guard the same way ms/iter
    rows do).  Returns [] when the trace holds no dispatch span."""
    from kmeans_tpu.obs.report import time_to_first_iteration
    try:
        table = time_to_first_iteration(records)
    except ValueError:
        return []
    return [{"metric": f"ttfi {r['phase']}", "ms": r["ms"]}
            for r in table]


def _bench_rows(doc) -> dict:
    """Comparable rows out of any bench artifact shape: BASELINE.json
    (``published.rows`` + the northstar), a BENCH_r*.json wrapper
    (``parsed``), a raw bench payload, a LIST of rows (JSONL
    artifacts parse to one), or a TTFI trace JSONL (span records —
    converted to per-phase ``ttfi <phase>`` rows).  Key = the row's
    ``metric`` else ``config``+``model``; same-key groups disambiguate
    instead of silently collapsing (review finding: 3 of the 4 serving
    rows were invisible to the guard)."""
    if isinstance(doc, list) and any(
            isinstance(r, dict) and r.get("kind") == "span"
            for r in doc):
        doc = _ttfi_trace_rows(doc)
    rows = []
    if isinstance(doc, dict) and "published" in doc:
        pub = doc["published"]
        rows.extend(r for r in pub.get("rows", [])
                    if isinstance(r, dict))
        if isinstance(pub.get("northstar"), dict):
            rows.append(pub["northstar"])
    elif isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        rows.append(doc["parsed"])
    elif isinstance(doc, list):
        rows.extend(r for r in doc if isinstance(r, dict))
    elif isinstance(doc, dict):
        rows.append(doc)
    groups: dict = {}
    for r in rows:
        key = r.get("metric") or r.get("config")
        if key is None:
            continue
        if r.get("model"):
            key = f"{key} [{r['model']}]"
        groups.setdefault(str(key), []).append(r)
    out = {}
    for key, grp in groups.items():
        if len(grp) == 1:
            out[key] = grp[0]
            continue
        for i, r in enumerate(grp):
            disc = next((f"{f}={r[f]}" for f in _BENCH_DISCRIMINATORS
                         if f in r), f"#{i + 1}")
            sub = f"{key} ({disc})"
            if sub in out:
                # Colliding discriminator values (e.g. an appended
                # re-measure of one batch size) still keep every row
                # comparable via the occurrence index.
                sub = f"{key} ({disc} #{i + 1})"
            out[sub] = r
    return out


def _row_spread(row: dict) -> float:
    """The largest noise figure a row RECORDED, whatever it called it:
    rows across rounds spell it ``spread``, ``overhead_spread``,
    ``speedup_spread``, ... — reading only ``spread`` would apply the
    5% floor to e.g. the BENCH_QUALITY row whose measured noise is
    19.6% under ``overhead_spread`` (review finding)."""
    vals = [v for k, v in row.items()
            if (k == "spread" or k.endswith("_spread"))
            and isinstance(v, (int, float)) and not isinstance(v, bool)]
    return max(vals, default=0.0)


def _bench_compare(old: dict, new: dict) -> dict:
    """One row pair -> list of per-field comparisons with the
    regression rule applied: the change in the WORSE direction must
    exceed the pair's recorded spread (max of both sides, floored at
    the 5% publication bar) to flag — the repo's own noise model, so a
    re-measure inside its error bars never pages anyone."""
    allow = max(_row_spread(old), _row_spread(new),
                _BENCH_DEFAULT_SPREAD)
    comps = []
    for field, lower_better in (
            [(f, True) for f in _BENCH_LOWER_BETTER]
            + [(f, False) for f in _BENCH_HIGHER_BETTER]):
        a, b = old.get(field), new.get(field)
        if not isinstance(a, (int, float)) \
                or not isinstance(b, (int, float)) \
                or isinstance(a, bool) or isinstance(b, bool) or a == 0:
            continue
        ratio = b / a
        worse = (ratio - 1.0) if lower_better else (1.0 - ratio)
        comps.append({"field": field, "old": a, "new": b,
                      "ratio": round(ratio, 4),
                      "allowed": round(allow, 4),
                      "regressed": bool(worse > allow)})
    return {"allow": allow, "fields": comps,
            "regressed": [c["field"] for c in comps if c["regressed"]]}


def bench_diff_main(argv=None) -> int:
    """``python -m kmeans_tpu bench-diff <old.json> <new.json>`` —
    compare two bench artifacts (BASELINE.json, BENCH_r*.json, or raw
    bench JSON lines) row by row and flag ratio regressions beyond
    each row's RECORDED spread (floored at the 5% publication bar) —
    the CI-runnable guard the bench trajectory lacked (ISSUE 14
    satellite).

    Rows are matched by ``metric``/``config`` key; rows present on
    only one side are reported informationally, never flagged.  Exit
    0: no regression.  Exit 1: at least one row regressed.  Exit 2:
    unreadable inputs or no common rows."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu bench-diff",
        description="Flag bench-row regressions beyond each row's "
                    "recorded spread between two bench JSON artifacts")
    parser.add_argument("old", help="baseline artifact (e.g. "
                                    "BASELINE.json, BENCH_r04.json)")
    parser.add_argument("new", help="candidate artifact")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable diff on stdout")
    args = parser.parse_args(argv)

    docs = []
    for path in (args.old, args.new):
        try:
            with open(path) as f:
                text = f.read()
            try:
                docs.append(json.loads(text))
            except ValueError:
                # JSONL fallback (review finding): the serving/obs
                # bench modes emit one JSON row PER LINE — parse to a
                # row list; a line that still fails is a real error.
                docs.append([json.loads(line)
                             for line in text.splitlines()
                             if line.strip()])
        except (OSError, ValueError) as e:
            print(f"error: cannot read bench artifact {path}: {e}",
                  file=sys.stderr)
            return 2
    rows_old, rows_new = _bench_rows(docs[0]), _bench_rows(docs[1])
    common = sorted(set(rows_old) & set(rows_new))
    if not common:
        print(f"error: no common bench rows between {args.old} "
              f"({len(rows_old)} rows) and {args.new} "
              f"({len(rows_new)} rows)", file=sys.stderr)
        return 2
    diff = {key: _bench_compare(rows_old[key], rows_new[key])
            for key in common}
    regressed = sorted(k for k, d in diff.items() if d["regressed"])
    result = {"old": args.old, "new": args.new,
              "rows_compared": len(common),
              "only_old": sorted(set(rows_old) - set(rows_new)),
              "only_new": sorted(set(rows_new) - set(rows_old)),
              "rows": diff, "regressed": regressed,
              "ok": not regressed}
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(f"bench-diff: {len(common)} rows compared "
              f"({len(result['only_old'])} only-old, "
              f"{len(result['only_new'])} only-new) — "
              f"{'OK' if not regressed else 'REGRESSED: ' + str(regressed)}")
        for key in common:
            for c in diff[key]["fields"]:
                flag = " <-- REGRESSION" if c["regressed"] else ""
                print(f"  {key[:44]:<44} {c['field']:<22} "
                      f"{c['old']:>12.4g} -> {c['new']:>12.4g} "
                      f"(x{c['ratio']:.3f}, allowed "
                      f"±{c['allowed']:.0%}){flag}")
    return 1 if regressed else 0


def lint_main(argv=None) -> int:
    """``python -m kmeans_tpu lint [--json] [paths]`` — the package's
    AST invariant linter (ISSUE 10; one rule per historical incident
    class, docs/ANALYSIS.md).  Thin delegator: the implementation lives
    in :mod:`kmeans_tpu.analysis.cli`; the analysis never imports or
    executes the modules it checks, so linting triggers no device
    initialization.  Exit 0 clean, 2 on findings or a malformed
    path."""
    from kmeans_tpu.analysis.cli import main
    return main(argv)


#: warm-command family table: model_class name -> import path the
#: loader resolves (every family's ``.load`` accepts any-family
#: checkpoints being rejected with a pointed error).
_WARM_FAMILIES = ("kmeans", "minibatch", "bisecting", "spherical", "gmm")


def _warm_class(name: str):
    import kmeans_tpu as kt
    table = {"kmeans": kt.KMeans, "minibatch": kt.MiniBatchKMeans,
             "bisecting": kt.BisectingKMeans,
             "spherical": kt.SphericalKMeans,
             "gmm": kt.GaussianMixture,
             # model_class names from checkpoint metadata
             "KMeans": kt.KMeans, "MiniBatchKMeans": kt.MiniBatchKMeans,
             "BisectingKMeans": kt.BisectingKMeans,
             "SphericalKMeans": kt.SphericalKMeans,
             "GaussianMixture": kt.GaussianMixture}
    return table.get(name)


def warm_main(argv=None) -> int:
    """``python -m kmeans_tpu warm <ckpt | --family F --shape NxD --k K>``
    — pre-populate the AOT executable cache for a (family, bucket,
    mesh, dtype) set (ISSUE 15 satellite): one synthetic fit at the
    bucketed shape compiles (or loads) the real step/fit/predict
    programs with the AOT store active, so the NEXT process — a fresh
    host resuming a shipped checkpoint, a standing fleet accepting a
    new fit — starts with ``compile(via='aot-load')`` rows instead of
    trace+compile.

    With a checkpoint argument the model's own hyperparameters drive
    the programs and the artifacts are ALSO mirrored into the sibling
    ``<ckpt>.aot`` directory (what ships with the checkpoint).  Prints
    what was compiled vs loaded; ``--json`` emits the machine-readable
    stats.  Exit 2 when the backend cannot serialize executables
    (``available=False``) or the arguments don't resolve."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu warm",
        description="Pre-populate the AOT executable cache for a "
                    "(family, bucket, mesh, dtype) set")
    parser.add_argument("ckpt", nargs="?", default=None,
                        help="checkpoint whose model (and sibling "
                             ".aot dir) to warm")
    parser.add_argument("--family", choices=_WARM_FAMILIES,
                        default="kmeans",
                        help="model family (no-checkpoint form)")
    parser.add_argument("--shape", default=None, metavar="NxD",
                        help="data shape to warm, e.g. 8192x32 "
                             "(default: 8192 rows x the checkpoint's "
                             "feature count)")
    parser.add_argument("--k", type=int, default=8,
                        help="clusters/components (no-checkpoint form)")
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--bucket", default="auto",
                        help="fit-shape bucket the programs commit to "
                             "(default auto)")
    parser.add_argument("--model-shards", type=int, default=1,
                        help="TP centroid-sharding axis size")
    parser.add_argument("--max-iter", type=int, default=None,
                        help="device-loop segment length to warm "
                             "(default: the model's max_iter)")
    parser.add_argument("--aot-dir", default=None, metavar="DIR",
                        help="store directory (default: "
                             "KMEANS_TPU_AOT_CACHE or "
                             "/tmp/kmeans_tpu_aot)")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    from kmeans_tpu.utils import aot
    aot.enable_compilation_cache()
    ok, reason = aot.aot_supported()
    if not ok:
        print(f"error: this backend cannot serialize compiled "
              f"executables ({reason}); the AOT cache is unavailable "
              f"(available=False)", file=sys.stderr)
        return 2
    try:
        bucket = _parse_bucket(args.bucket)
    except ValueError:
        print(f"error: --bucket must be 'auto' or an int, got "
              f"{args.bucket!r}", file=sys.stderr)
        return 2
    root = args.aot_dir or os.environ.get("KMEANS_TPU_AOT_CACHE") \
        or "/tmp/kmeans_tpu_aot"
    mirror = aot.aot_dir_for(args.ckpt) if args.ckpt else None
    store = aot.configure(root, mirror=mirror)

    d = None
    if args.ckpt:
        from kmeans_tpu.utils.checkpoint import describe_checkpoint
        info = describe_checkpoint(args.ckpt)
        cls = _warm_class(info.get("model_class") or "")
        if cls is None:
            print(f"error: {args.ckpt}: no loadable model "
                  f"(model_class={info.get('model_class')!r}, "
                  f"primary_error={info.get('primary_error')!r})",
                  file=sys.stderr)
            return 2
        model = cls.load(args.ckpt)
        table = getattr(model, "centroids", None)
        if table is None:
            table = getattr(model, "means_", None)
        d = int(np.asarray(table).shape[1]) if table is not None else None
    else:
        cls = _warm_class(args.family)
        kwargs = dict(seed=0, verbose=False)
        model = cls(**({"n_components": args.k} if args.family == "gmm"
                       else {"k": args.k}), dtype=args.dtype, **kwargs)
    if args.shape:
        try:
            n, d = (int(v) for v in args.shape.lower().split("x"))
        except ValueError:
            print(f"error: --shape must be NxD (e.g. 8192x32), got "
                  f"{args.shape!r}", file=sys.stderr)
            return 2
    else:
        n = 8192
        if d is None:
            print("error: --shape NxD is required without a fitted "
                  "checkpoint (the feature count cannot be inferred)",
                  file=sys.stderr)
            return 2
    if args.max_iter is not None:
        model.max_iter = args.max_iter
    model.bucket = bucket
    if hasattr(model, "model_shards"):
        model.model_shards = args.model_shards
        model.mesh = None                       # re-resolve for the TP axis
    # The warm fit: synthetic rows at the bucketed shape through the
    # REAL fit engine (device loop where the family has one), so the
    # programs warmed are the programs a real fit keys.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(model.dtype)
    model.verbose = False
    if hasattr(model, "host_loop"):
        model.host_loop = False
    fit_k = getattr(model, "k", getattr(model, "n_components", 2))
    if n < fit_k:
        print(f"error: shape rows ({n}) must be >= k ({fit_k})",
              file=sys.stderr)
        return 2
    model.fit(X)
    stats = store.stats()
    out = {"family": type(model).__name__, "n": n, "d": d,
           "k": int(fit_k), "bucket": bucket,
           "dtype": str(np.dtype(model.dtype)),
           "ckpt": args.ckpt, **stats}
    if args.json:
        print(json.dumps(out))
    else:
        print(f"warm: {out['family']} k={out['k']} {n}x{d} "
              f"bucket={bucket} -> compiled {stats['built']}, "
              f"loaded {stats['loaded']} (store {stats['root']}"
              + (f", shipped to {stats['mirror']}" if stats["mirror"]
                 else "") + ")")
    return 0


def _ckpt_plan(path, info: dict, plan_n: int) -> dict:
    """The ckpt-info planner block (ISSUE 16): r16 ``plan_fit`` rows
    for this checkpoint's table on its written-on mesh at ``plan_n``
    rows, plus the ``k_shard``/``assign`` resolution — the state's own
    explicit knobs when it carries them, the fit-time auto rule
    otherwise."""
    import json as _json
    from kmeans_tpu.obs import memory as _mem
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    from kmeans_tpu.utils.checkpoint import prev_path
    src = path if info["source"] == "primary" else str(prev_path(path))
    with np.load(src, allow_pickle=False) as z:
        meta = _json.loads(str(z["__meta__"]))
        name = "centroids" if "centroids" in z.files else next(
            f for f in z.files
            if f != "__meta__" and z[f].ndim == 2)
        d = int(z[name].shape[1])
    mesh = info.get("written_on_mesh") or {}
    S = int(mesh.get("data_shards") or 1)
    M = int(mesh.get("model_shards") or 1)
    k = int(info["k"])
    dtype = str(info.get("dtype") or "float32")
    chunk = choose_chunk_size(-(-plan_n // S), max(k, M), d)
    plans = [_mem.plan_fit("kmeans", plan_n, d, k, data_shards=S,
                           model_shards=M, dtype=dtype, chunk=chunk,
                           k_shard=0)]
    if M > 1:
        plans.append(_mem.plan_fit("kmeans", plan_n, d, k,
                                   data_shards=S, model_shards=M,
                                   dtype=dtype, chunk=chunk, k_shard=M))
    dev = _mem.device_memory_info()
    fits = True
    if dev.get("available"):
        fits = plans[0]["predicted_peak_bytes"] <= 0.8 * dev["bytes_free"]
    ks, asg = meta.get("k_shard", "auto"), meta.get("assign", "auto")
    # Any explicit knob in the state wins over the auto rule; only a
    # fully-'auto' state reports a purely rule-driven resolution.
    src_note = "auto rule" if (ks == "auto" and asg == "auto") \
        else "checkpoint knobs"
    if ks == "auto":
        ks = 0 if (fits or M <= 1) else M
    if asg == "auto":
        asg = "dense" if (fits or M > 1) else "two_level"
    return {"n_assumed": int(plan_n), "d": d, "k": k,
            "data_shards": S, "model_shards": M, "chunk": chunk,
            "plans": plans,
            "k_shard": int(ks), "assign": asg,
            "resolved_by": src_note,
            "table_bytes_per_device":
                plans[-1]["components"]["table_bytes"]}


def ckpt_info_main(argv=None) -> int:
    """``python -m kmeans_tpu ckpt-info <path>`` — print a checkpoint's
    metadata block (model class, k, completed iteration, the mesh shape
    it was written on, format/jax versions) and whether the ``.prev``
    last-good rotation exists and loads: the operator-facing half of
    torn-checkpoint debugging (ISSUE 5).  Exit code 0 when a usable
    state was found (primary OR ``.prev``), 2 otherwise."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu ckpt-info",
        description="Describe a kmeans_tpu checkpoint (topology "
                    "metadata + last-good rotation status)")
    parser.add_argument("path", help="checkpoint path (.npz)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON only")
    parser.add_argument("--plan-n", type=int, default=1_000_000,
                        metavar="N",
                        help="rows assumed for the HBM planner block "
                             "(ISSUE 16; default 1e6 — the table-side "
                             "terms dominate at massive k)")
    args = parser.parse_args(argv)

    from kmeans_tpu.utils.checkpoint import describe_checkpoint
    info = describe_checkpoint(args.path)
    # AOT block (ISSUE 15 satellite): the executables shipped next to
    # this checkpoint (<path>.aot), described without device init.
    from kmeans_tpu.utils import aot
    info["aot"] = aot.describe_dir(aot.aot_dir_for(args.path))
    # Large-k planner block (ISSUE 16): the per-device table footprint
    # and the k_shard/assign resolution this state would get at
    # --plan-n rows on its written-on mesh, in the r16 planner's
    # format.  Needs the table's D: read ONE member's shape from the
    # loadable source (lazy per-member np.load — the payload arrays
    # stay compressed); any failure skips the block, never the report.
    info["plan"] = None
    if info.get("source") and info.get("k"):
        try:
            info["plan"] = _ckpt_plan(args.path, info, args.plan_n)
        except Exception:       # noqa: BLE001 — the block is optional
            info["plan"] = None
    if args.json:
        print(json.dumps(info, indent=2))
        return 0 if info.get("source") else 2
    if info.get("source") is None:
        print(f"error: {info['path']}: no loadable state "
              f"(primary: {info.get('primary_error')}; "
              f".prev exists: {info['prev_exists']}"
              + (f", loads: {info.get('prev_loads')}"
                 if info["prev_exists"] else "") + ")",
              file=sys.stderr)
        return 2
    mesh = info.get("written_on_mesh") or {}
    lines = [
        f"checkpoint      : {info['path']}  [read from "
        f"{info['source']}]",
        f"model           : {info.get('model_class')} "
        f"(k={info.get('k')}, iteration {info.get('iteration')})",
        f"written on mesh : data_shards="
        f"{mesh.get('data_shards')}, model_shards="
        f"{mesh.get('model_shards')} (informational — state is "
        f"canonical; resume re-shards for any topology)",
        f"format version  : {info.get('format_version')}   "
        f"jax {info.get('jax_version')}   dtype {info.get('dtype')}",
        f".prev rotation  : exists={info['prev_exists']}"
        + (f", loads={info['prev_loads']}" if info["prev_exists"]
           else ""),
    ]
    a = info["aot"]
    if a["exists"]:
        progs = ", ".join(f"{p['cache']}@{p['platform']}"
                          for p in a["programs"]) or "-"
        lines.append(
            f"aot executables : {a['artifacts']} artifacts "
            f"({a['bytes']:,} B) in {a['path']} [{progs}]"
            + (f", {a['unreadable']} unreadable" if a["unreadable"]
               else ""))
    else:
        lines.append(
            "aot executables : none shipped (run `python -m kmeans_tpu "
            "warm <ckpt>` to pre-populate)")
    p = info.get("plan")
    if p:
        from kmeans_tpu.obs.memory import _fmt_bytes
        lines.append(
            f"table footprint : "
            f"{_fmt_bytes(p['table_bytes_per_device'])}/device "
            f"(k={p['k']}, d={p['d']}, {p['data_shards']}x"
            f"{p['model_shards']} mesh)")
        lines.append(
            f"large-k route   : k_shard={p['k_shard']}, "
            f"assign={p['assign']!r}  [{p['resolved_by']}; planned at "
            f"n={p['n_assumed']:,}, predicted peak "
            f"{_fmt_bytes(p['plans'][-1]['predicted_peak_bytes'])}"
            f"/device]")
    if info.get("primary_error"):
        lines.append(f"primary error   : {info['primary_error']}")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
