"""``python -m kmeans_tpu fit`` — cluster an on-disk matrix from the shell.

The reference has no CLI at all (SURVEY.md §1: its ``__main__`` takes no
arguments); this is a superset utility: point it at a ``.npy`` (or ``.npz``
key) of shape (n, D), get centroids/labels/summary artifacts back.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

_MODELS = ("kmeans", "minibatch", "bisecting", "spherical")


def _load_matrix(path: str, npz_key: str) -> np.ndarray:
    p = Path(path)
    if not p.exists():
        raise FileNotFoundError(f"no such file: {p}")
    if p.suffix == ".npz":
        with np.load(p) as z:
            keys = list(z.keys())
            if not keys:
                raise ValueError(f"{p} contains no arrays")
            key = npz_key or keys[0]
            if key not in keys:
                raise KeyError(f"{p} has no array {key!r}; "
                               f"available: {keys}")
            return np.asarray(z[key])
    return np.load(p)


def _build_model(args):
    from kmeans_tpu import (BisectingKMeans, KMeans, MiniBatchKMeans,
                            SphericalKMeans)
    common = dict(k=args.k, max_iter=args.max_iter, tolerance=args.tolerance,
                  seed=args.seed, compute_sse=args.sse, init=args.init,
                  n_init=args.n_init, verbose=not args.quiet)
    if args.model == "minibatch":
        # n_init > 1 selects the best-scoring candidate init
        # (sklearn-style), then runs one training session.
        return MiniBatchKMeans(batch_size=args.batch_size, **common)
    if args.model == "bisecting":
        return BisectingKMeans(**common)      # n_init applies per split
    if args.model == "spherical":
        return SphericalKMeans(**common)
    return KMeans(**common)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu fit",
        description="Cluster an (n, D) .npy/.npz matrix on TPU/CPU devices")
    parser.add_argument("data", help="path to .npy or .npz with (n, D) floats")
    parser.add_argument("--npz-key", default="",
                        help=".npz array name (default: first key)")
    parser.add_argument("--k", type=int, required=True)
    parser.add_argument("--model", choices=_MODELS, default="kmeans")
    parser.add_argument("--max-iter", type=int, default=100)
    parser.add_argument("--tolerance", type=float, default=1e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--init", default="forgy",
                        help="forgy | kmeans++ | kmeans|| (default forgy)")
    parser.add_argument("--n-init", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=4096,
                        help="minibatch model only")
    parser.add_argument("--sse", action="store_true",
                        help="track SSE per iteration")
    parser.add_argument("--out-dir", default=".",
                        help="where centroids.npy/labels.npy/summary.json go")
    parser.add_argument("--no-labels", action="store_true",
                        help="skip writing per-point labels")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    try:
        X = _load_matrix(args.data, args.npz_key)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if X.ndim != 2:
        print(f"error: expected (n, D) matrix, got shape {X.shape}",
              file=sys.stderr)
        return 2
    model = _build_model(args)

    X = np.asarray(X, dtype=np.float32)
    start = time.perf_counter()
    model.fit(X)
    elapsed = time.perf_counter() - start
    # Real final inertia even without --sse (one fused pass).
    inertia = model.inertia_ if model.inertia_ is not None \
        else -model.score(X)

    out = Path(args.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.save(out / "centroids.npy", model.centroids)
    if not args.no_labels:
        np.save(out / "labels.npy", model.labels_)
    summary = {
        "model": args.model, "n": int(X.shape[0]), "d": int(X.shape[1]),
        "k": args.k, "iterations": model.iterations_run,
        "fit_seconds": round(elapsed, 3),
        "inertia": float(inertia),
        "sse_history": [float(s) for s in model.sse_history],
        "cluster_sizes": [int(c) for c in model.cluster_sizes_]
        if model.cluster_sizes_ is not None else None,
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    if not args.quiet:
        print(json.dumps(summary))
    return 0


def ckpt_info_main(argv=None) -> int:
    """``python -m kmeans_tpu ckpt-info <path>`` — print a checkpoint's
    metadata block (model class, k, completed iteration, the mesh shape
    it was written on, format/jax versions) and whether the ``.prev``
    last-good rotation exists and loads: the operator-facing half of
    torn-checkpoint debugging (ISSUE 5).  Exit code 0 when a usable
    state was found (primary OR ``.prev``), 2 otherwise."""
    parser = argparse.ArgumentParser(
        prog="python -m kmeans_tpu ckpt-info",
        description="Describe a kmeans_tpu checkpoint (topology "
                    "metadata + last-good rotation status)")
    parser.add_argument("path", help="checkpoint path (.npz)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON only")
    args = parser.parse_args(argv)

    from kmeans_tpu.utils.checkpoint import describe_checkpoint
    info = describe_checkpoint(args.path)
    if args.json:
        print(json.dumps(info, indent=2))
        return 0 if info.get("source") else 2
    if info.get("source") is None:
        print(f"error: {info['path']}: no loadable state "
              f"(primary: {info.get('primary_error')}; "
              f".prev exists: {info['prev_exists']}"
              + (f", loads: {info.get('prev_loads')}"
                 if info["prev_exists"] else "") + ")",
              file=sys.stderr)
        return 2
    mesh = info.get("written_on_mesh") or {}
    lines = [
        f"checkpoint      : {info['path']}  [read from "
        f"{info['source']}]",
        f"model           : {info.get('model_class')} "
        f"(k={info.get('k')}, iteration {info.get('iteration')})",
        f"written on mesh : data_shards="
        f"{mesh.get('data_shards')}, model_shards="
        f"{mesh.get('model_shards')} (informational — state is "
        f"canonical; resume re-shards for any topology)",
        f"format version  : {info.get('format_version')}   "
        f"jax {info.get('jax_version')}   dtype {info.get('dtype')}",
        f".prev rotation  : exists={info['prev_exists']}"
        + (f", loads={info['prev_loads']}" if info["prev_exists"]
           else ""),
    ]
    if info.get("primary_error"):
        lines.append(f"primary error   : {info['primary_error']}")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
