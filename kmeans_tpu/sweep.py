"""Model-selection sweeps: fit-many, pick-best, in O(1) dispatches.

The single most common clustering workflow is choosing k: users run
k_max sequential fits and eyeball an elbow / silhouette / BIC curve,
paying k_max full dispatch+compile+fit costs for work that is
embarrassingly batchable.  This module holds the family-agnostic half
of the sweep engine (ISSUE 7):

* ``parse_k_range`` — one grammar for CLI strings ("2:33", "2:33:2",
  "2,4,8"), Python ranges, and explicit iterables;
* ``SweepResult`` — per-k per-restart final scores, the criterion
  curve, the selected k, and the fitted best model (trimmed to its
  real k);
* ``select_k`` — the selection rules, including the elbow rule for the
  monotone-decreasing inertia criterion (raw argmin would always pick
  k_max);
* ``clone_for`` — estimator cloning via the sklearn param protocol, so
  sweep members inherit every config knob of the model they sweep.

The family-specific halves live on the estimators:
``KMeans.sweep`` / ``SphericalKMeans.sweep`` (criteria: inertia /
silhouette / calinski_harabasz / davies_bouldin) and
``GaussianMixture.sweep`` (bic / aic).  Both extend the batched-restart
machinery (``parallel.distributed.make_multi_fit_fn`` /
``parallel.gmm_step.make_gmm_multi_fit_fn``): the member axis ranges
over k as well as seeds, every member padded to k_max with inert
components — sentinel centroid rows for the K-Means family, the r10
pad constants (zero mean, unit variance, -inf log-weight) for GMM — so
an elbow sweep over k ∈ {2..k_max} × n_init restarts is ONE vmapped
device dispatch instead of k_max·n_init sequential fits.
``sweep(batched=0)`` runs the sequential per-member oracle instead —
the parity reference every batched member must match at its seed
(bit-exact for the K-Means f64 device-loop class; documented
reduction class otherwise).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

#: criterion -> optimization direction, per family.  'inertia' is
#: special-cased in ``select_k`` (elbow rule — inertia is monotone
#: decreasing in k, so raw argmin would degenerate to k_max).
KMEANS_CRITERIA = {"inertia": "min", "silhouette": "max",
                   "calinski_harabasz": "max", "davies_bouldin": "min"}
GMM_CRITERIA = {"bic": "min", "aic": "min"}


def parse_k_range(spec) -> Tuple[int, ...]:
    """Normalize a k-range spec to a sorted tuple of distinct ints >= 1.

    Accepts the CLI grammar ``"lo:hi"`` / ``"lo:hi:step"`` (half-open,
    Python ``range`` semantics: ``"2:33"`` is k ∈ {2..32}) and
    ``"2,4,8"`` comma lists, plus any Python iterable of ints (``range``
    objects included).  Raises ``ValueError`` on anything malformed or
    empty — the CLI maps that to exit code 2."""
    if isinstance(spec, str):
        s = spec.strip()
        try:
            if ":" in s:
                parts = [int(p) for p in s.split(":")]
                if len(parts) == 2:
                    ks = list(range(parts[0], parts[1]))
                elif len(parts) == 3:
                    ks = list(range(parts[0], parts[1], parts[2]))
                else:
                    raise ValueError
            else:
                ks = [int(p) for p in s.split(",")]
        except ValueError:
            raise ValueError(
                f"invalid k range {spec!r}: expected 'lo:hi[:step]' "
                f"(half-open) or a comma list like '2,4,8'") from None
    elif isinstance(spec, (int, np.integer)):
        raise ValueError(
            f"k_range must span several k values (a range or list), got "
            f"the single int {spec!r}; for one k just call fit")
    else:
        ks = [int(k) for k in spec]
    ks = sorted(set(ks))
    if not ks:
        raise ValueError(f"k range {spec!r} is empty")
    if ks[0] < 1:
        raise ValueError(f"k range {spec!r} contains k < 1")
    return tuple(ks)


def check_criterion(criterion: str, table: dict) -> str:
    if criterion not in table:
        raise ValueError(f"unknown criterion {criterion!r}; valid: "
                         f"{sorted(table)}")
    return table[criterion]


def elbow_index(ks, inertias) -> int:
    """Elbow of a (k, inertia) curve: the point with the maximum
    normalized distance BELOW the chord joining the curve's endpoints
    (the kneedle rule).  Inertia decreases monotonically in k, so the
    raw minimum is always k_max — the elbow is where adding clusters
    stops paying.  Degenerate inputs (fewer than 3 points, or a curve
    never below its chord — no convex knee) fall back to the minimum-
    inertia index, documented in ``KMeans.sweep``."""
    y = np.asarray(inertias, np.float64)
    finite = np.isfinite(y)
    if len(ks) < 3 or not np.all(finite):
        masked = np.where(finite, y, np.inf)
        return int(np.argmin(masked))
    x = np.asarray(ks, np.float64)
    x = (x - x[0]) / max(x[-1] - x[0], 1e-300)
    span = max(float(y.max() - y.min()), 1e-300)
    yn = (y - y.min()) / span
    chord = yn[0] + (yn[-1] - yn[0]) * x
    below = chord - yn                       # >0 where the curve dips
    i = int(np.argmax(below))
    if below[i] <= 0:                        # concave/flat: no knee
        return int(np.argmin(y))
    return i


def select_k(ks, scores, criterion: str) -> int:
    """The selected k for a per-k criterion curve (see the criteria
    tables; 'inertia' routes through the elbow rule)."""
    scores = np.asarray(scores, np.float64)
    if not np.any(np.isfinite(scores)):
        raise ValueError(
            f"no finite {criterion} score in the sweep (every member "
            f"failed); inspect SweepResult.member_scores")
    if criterion == "inertia":
        return int(ks[elbow_index(ks, scores)])
    direction = {**KMEANS_CRITERIA, **GMM_CRITERIA}[criterion]
    masked = np.where(np.isfinite(scores), scores,
                      -np.inf if direction == "max" else np.inf)
    pick = np.argmax(masked) if direction == "max" else np.argmin(masked)
    return int(ks[int(pick)])


def within_k_winners(member_vals, n_k: int, n_init: int,
                     maximize: bool = False):
    """Within-k restart selection over per-member fit values (the
    n_init rule; K-Means: lowest true final inertia, GMM: highest final
    lower bound).  Non-finite members can never win.  Returns
    ``(vals, best_r, win_idx)`` — the values reshaped ``(n_k, n_init)``,
    each k's winning restart index, and the winners' flat member ids.
    ONE implementation for both families: the masking/tie rule must
    not silently diverge between them."""
    vals = np.asarray(member_vals, np.float64).reshape(n_k, n_init)
    masked = np.where(np.isfinite(vals),
                      vals, -np.inf if maximize else np.inf)
    best_r = (np.argmax if maximize else np.argmin)(masked, axis=1)
    win_idx = np.arange(n_k) * n_init + best_r
    return vals, best_r, win_idx


def selected_member(ks, scores, criterion: str, win_idx):
    """Resolve the criterion curve to ``(selected_k, sel, m_sel)``:
    the chosen k, its index in ``ks``, and its winning restart's flat
    member id (the model the sweep publishes)."""
    selected_k = select_k(ks, scores, criterion)
    sel = int(np.flatnonzero(np.asarray(ks) == selected_k)[0])
    return selected_k, sel, int(win_idx[sel])


def clone_for(model, **overrides):
    """A fresh estimator of ``model``'s class with its constructor
    params (sklearn ``get_params`` protocol) plus ``overrides`` — how
    sweep members inherit every config knob (dtype, mesh, distance
    mode, empty policy, ...) of the model they sweep."""
    params = model.get_params()
    params.update(overrides)
    return type(model)(**params)


@dataclasses.dataclass
class SweepResult:
    """Outcome of a ``.sweep(k_range=...)`` model-selection run.

    ``scores[i]`` is the criterion value of k_range[i]'s winning
    restart; ``member_scores[i, r]`` is the per-member FIT score
    (K-Means family: true final inertia; GMM: final lower bound) that
    selected the restart within each k.  ``n_dispatches`` counts the
    engine's fit/score device dispatches — O(1) in |k_range| on the
    batched path (the init row draws are O(|k_range|) tiny gathers,
    not fit dispatches)."""

    family: str
    criterion: str
    k_range: Tuple[int, ...]
    scores: np.ndarray                  # (n_k,)
    member_scores: np.ndarray           # (n_k, n_init)
    selected_k: int
    selected_restart: int
    best_model: object
    n_dispatches: int
    batched: bool
    n_iters: Optional[np.ndarray] = None      # (n_k, n_init)

    def summary(self) -> dict:
        """JSON-able summary (the CLI's ``--json`` payload)."""
        return {
            "family": self.family,
            "criterion": self.criterion,
            "k_range": [int(k) for k in self.k_range],
            "selected_k": int(self.selected_k),
            "selected_restart": int(self.selected_restart),
            "scores": {str(k): (None if not np.isfinite(s) else float(s))
                       for k, s in zip(self.k_range, self.scores)},
            "member_scores": [[(None if not np.isfinite(s) else float(s))
                               for s in row]
                              for row in np.asarray(self.member_scores)],
            "dispatches": int(self.n_dispatches),
            "batched": bool(self.batched),
        }
