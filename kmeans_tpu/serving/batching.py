"""Micro-batching request queue for the online serving engine (ISSUE 6).

Clipper-style adaptive micro-batching: concurrent small ``predict`` /
``score`` requests against the SAME resident model are coalesced into
one padded device dispatch, amortizing the per-call dispatch latency
(on a tunneled chip the ~70-100 ms RTT documented in
docs/PERFORMANCE.md IS the request cost at serving batch sizes).  The
queue is deliberately model-agnostic: it coalesces opaque row blocks
per ``(model_id, op)`` key and hands one concatenated block to an
injected ``dispatch`` callable — the engine (serving.engine) owns all
device/state concerns.

Contract (pinned by tests/test_serving_queue.py):

* **Per-model coalescing only.**  A flushed batch contains rows for
  exactly one ``(model_id, op)`` key — rows are NEVER mixed across
  models inside a dispatch buffer.  (Cross-model single-dispatch
  routing is the engine's separate packed-assign machinery,
  ``ServingEngine.predict_multi``.)
* **Flush on full or on timer.**  A group flushes as soon as its
  pending rows reach the largest batch bucket (flush-on-full, run in
  the submitting thread — deterministic even without the worker), or
  once its OLDEST request has waited ``max_wait_ms`` (flush-on-timer,
  run by the background worker — or by an explicit ``service(now=...)``
  call, which is how the tests drive the timer with an injected
  clock).
* **Order-preserving slices.**  Within a batch, requests keep
  submission order and each future receives exactly its own rows'
  slice of the dispatch result (axis 0 aligned with the concatenated
  input rows).
* **Error isolation.**  A request that fails validation errors its OWN
  future at submit time and never enters a batch.  A dispatch-time
  failure of a coalesced batch re-dispatches each member request
  INDIVIDUALLY, so one poisoned request fails alone and the rest of
  the batch still succeeds (and a transient dispatch fault — e.g.
  ``utils.faults.fail_first_attempts`` — costs one isolation round,
  not the whole batch).
* **Clean shutdown, no leaked threads.**  ``close()`` drains pending
  groups (flushing them so no future is left unresolved), joins the
  worker, and is idempotent — the ``data.prefetch`` shutdown
  discipline.  Requests submitted after close fail with
  :class:`ServingClosedError`.

The clock is injectable (``clock=``) so the timer semantics are testable
without real sleeps; ``start=False`` skips the worker thread entirely
(flush-on-full still works inline; timers fire only via ``service``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kmeans_tpu.obs import trace as _obs_trace

__all__ = ["MicroBatchQueue", "ServingFuture", "ServingClosedError",
           "DEFAULT_BUCKETS"]

# Bucketed batch sizes: a dispatch pads its rows up to the smallest
# bucket that fits (compile once per bucket instead of once per distinct
# request size).  Oversize batches round up to a multiple of the largest
# bucket.
DEFAULT_BUCKETS = (8, 64, 512, 4096)


class ServingClosedError(RuntimeError):
    """The queue (or engine) was closed before this request could run."""


class ServingFuture:
    """Minimal completion handle for one submitted request.

    ``result(timeout=None)`` blocks until the request's batch is
    dispatched and returns this request's own slice of the output (or
    re-raises the request's error).  Thread-safe; a future resolves
    exactly once.
    """

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serving request not completed within "
                               f"{timeout!r} s")
        if self._error is not None:
            raise self._error
        return self._value

    def exception(self, timeout: Optional[float] = None):
        """The request's error (None on success) — without raising."""
        if not self._event.wait(timeout):
            raise TimeoutError("serving request not completed within "
                               f"{timeout!r} s")
        return self._error

    # -- producer side (queue internal) --
    def _set_result(self, value) -> None:
        self._value = value
        self._event.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()


class _Pending:
    """One queued request: validated rows + its future + enqueue time."""

    __slots__ = ("rows", "m", "future", "t")

    def __init__(self, rows: np.ndarray, future: ServingFuture, t: float):
        self.rows = rows
        self.m = int(rows.shape[0])
        self.future = future
        self.t = t


def check_buckets(buckets) -> Tuple[int, ...]:
    """Validate a bucket ladder: strictly positive ints, deduped,
    ascending."""
    bs = tuple(sorted({int(b) for b in buckets}))
    if not bs or bs[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    return bs


def bucket_for(m: int, buckets: Tuple[int, ...]) -> int:
    """Padded dispatch size for ``m`` rows: the smallest bucket that
    fits, else the next multiple of the largest bucket (oversize
    requests stay bounded to a few distinct compiled shapes)."""
    for b in buckets:
        if m <= b:
            return b
    top = buckets[-1]
    return -(-m // top) * top


class MicroBatchQueue:
    """Coalesce concurrent requests per ``(model_id, op)`` into one
    dispatch.

    Parameters
    ----------
    dispatch : callable ``(model_id, op, rows) -> array``
        Runs one coalesced batch; must return an array whose axis 0
        aligns 1:1 with the input rows (the queue slices per request).
    buckets : ascending batch-size ladder (informational here — the
        ENGINE pads to buckets; the queue uses ``buckets[-1]`` as the
        flush-on-full threshold and the per-dispatch row cap).
    max_wait_ms : float
        Longest a request may sit waiting for co-batchable traffic
        before its group flushes (the latency/throughput knob).
    clock : callable () -> float, default ``time.monotonic``
        Injectable time source — deterministic timer tests drive
        ``service(now=...)`` against a fake clock.
    start : bool
        Start the background flush worker.  ``False`` = no thread:
        flush-on-full still runs inline in ``submit``; timer flushes
        happen only on explicit ``service()`` calls.
    validate : callable ``(model_id, op, rows) -> np.ndarray`` or None
        Maps/validates raw request rows to the canonical (m, D) block
        BEFORE enqueueing; an exception here fails ONLY this request's
        future (submit-time poison isolation).
    """

    def __init__(self, dispatch: Callable, *,
                 buckets=DEFAULT_BUCKETS, max_wait_ms: float = 2.0,
                 clock: Optional[Callable[[], float]] = None,
                 start: bool = True, validate: Optional[Callable] = None):
        self._dispatch = dispatch
        self._buckets = check_buckets(buckets)
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._max_wait = float(max_wait_ms) / 1e3
        self._clock = clock if clock is not None else time.monotonic
        self._validate = validate
        self._cv = threading.Condition()
        self._groups: Dict[tuple, List[_Pending]] = {}
        self._closed = False
        # Observability: dispatches run, requests/rows coalesced, and a
        # per-dispatch request-count histogram (the engine layers its
        # bucket-fill histogram on top).
        self.dispatches = 0
        self.requests = 0
        self.rows = 0
        self.coalesce_hist: Dict[int, int] = {}
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name="kmeans_tpu-serving-flush",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- submit

    def submit(self, model_id, rows, *, op: str = "predict"
               ) -> ServingFuture:
        """Enqueue one request; returns its :class:`ServingFuture`.

        Validation errors (shape/dtype/non-finite rows, unknown model —
        whatever the injected ``validate`` raises) resolve THIS future
        with the error immediately: a poisoned request never taints a
        batch."""
        fut = ServingFuture()
        try:
            block = self._validate(model_id, op, rows) \
                if self._validate is not None else np.asarray(rows)
        except Exception as e:              # noqa: BLE001 — per-request
            fut._set_error(e)
            return fut
        full_batch = None
        with self._cv:
            if self._closed:
                fut._set_error(ServingClosedError(
                    "serving queue is closed"))
                return fut
            key = (model_id, op)
            group = self._groups.setdefault(key, [])
            group.append(_Pending(block, fut, self._clock()))
            self.requests += 1
            if sum(p.m for p in group) >= self._buckets[-1]:
                # Flush-on-full runs in the SUBMITTING thread (outside
                # the lock): deterministic without the worker, and the
                # submitter pays for the dispatch it completed.
                full_batch = self._take_batch(group)
                if not group:
                    del self._groups[key]
            else:
                self._cv.notify()
        if full_batch is not None:
            self._run_batch(key, full_batch)
        return fut

    # ------------------------------------------------------------- flush

    def _take_batch(self, group: List[_Pending]) -> List[_Pending]:
        """Pop the FIFO prefix whose rows fit in one dispatch (cap =
        the largest bucket); a single oversize request rides alone."""
        cap = self._buckets[-1]
        batch = [group.pop(0)]
        total = batch[0].m
        while group and total + group[0].m <= cap:
            p = group.pop(0)
            batch.append(p)
            total += p.m
        return batch

    def service(self, now: Optional[float] = None) -> int:
        """Flush every group that is due (oldest request waited
        ``max_wait_ms``) or already full.  Returns the number of
        dispatches run.  The worker calls this with the real clock;
        tests call it directly with an injected ``now``."""
        if now is None:
            now = self._clock()
        batches = []
        with self._cv:
            for key in list(self._groups):
                group = self._groups[key]
                while group and (
                        group[0].t + self._max_wait <= now
                        or sum(p.m for p in group) >= self._buckets[-1]):
                    batches.append((key, self._take_batch(group)))
                if not group:
                    del self._groups[key]
        for key, batch in batches:
            self._run_batch(key, batch)
        return len(batches)

    def _next_deadline(self) -> Optional[float]:
        """Earliest group deadline (caller holds the lock)."""
        ts = [g[0].t for g in self._groups.values() if g]
        return (min(ts) + self._max_wait) if ts else None

    def _run_batch(self, key: tuple, batch: List[_Pending]) -> None:
        model_id, op = key
        rows = batch[0].rows if len(batch) == 1 else \
            np.concatenate([p.rows for p in batch], axis=0)
        # Counters mutate under the lock: flush-on-full (submitter
        # thread) and timer flushes (worker) run _run_batch
        # concurrently, and stats() snapshots read these from yet other
        # threads.
        with self._cv:
            self.dispatches += 1
            self.rows += rows.shape[0]
            self.coalesce_hist[len(batch)] = \
                self.coalesce_hist.get(len(batch), 0) + 1
        try:
            # 'serve.flush' span (ISSUE 11): one queue flush — the
            # coalesced dispatch it runs emits its own nested
            # 'serve.request' span from the engine.
            with _obs_trace.span("serve.flush", model=str(model_id),
                                 op=op, coalesced=len(batch),
                                 rows=int(rows.shape[0])):
                out = self._dispatch(model_id, op, rows)
        except Exception as batch_err:      # noqa: BLE001 — isolated below
            if len(batch) == 1:
                batch[0].future._set_error(batch_err)
                return
            # Error isolation: re-dispatch each member alone so only the
            # poisoned request(s) fail; a transient batch fault costs one
            # isolation round.
            for p in batch:
                with self._cv:
                    self.dispatches += 1
                try:
                    p.future._set_result(self._dispatch(model_id, op,
                                                        p.rows))
                except Exception as e:      # noqa: BLE001 — per-request
                    p.future._set_error(e)
            return
        off = 0
        for p in batch:
            p.future._set_result(out[off: off + p.m])
            off += p.m

    def stats(self) -> dict:
        """Consistent counter snapshot (copies taken under the lock —
        safe against concurrent flushes)."""
        with self._cv:
            return {"dispatches": self.dispatches,
                    "requests": self.requests,
                    "rows": self.rows,
                    "coalesce_hist": dict(sorted(
                        self.coalesce_hist.items()))}

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed and not self._groups:
                    return
                deadline = self._next_deadline()
                if deadline is None:
                    self._cv.wait()
                else:
                    self._cv.wait(timeout=max(deadline - self._clock(),
                                              0.0))
                if self._closed and not self._groups:
                    return
            self.service()

    # ----------------------------------------------------------- shutdown

    def pending(self) -> int:
        with self._cv:
            return sum(len(g) for g in self._groups.values())

    def close(self) -> None:
        """Drain-and-join: flush every pending group (no future is left
        unresolved), stop and join the worker.  Idempotent — the
        ``data.prefetch`` shutdown discipline."""
        with self._cv:
            if self._closed and not self._groups and (
                    self._thread is None or not self._thread.is_alive()):
                return
            self._closed = True
            self._cv.notify_all()
        # Drain in THIS thread (service with an infinite 'now' flushes
        # every group regardless of age); the worker may race us to
        # individual batches — both paths pop under the lock, so each
        # batch dispatches exactly once.
        self.service(now=math.inf)
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:       # interpreter shutdown — nothing to do
            pass
