"""Multi-model residency registry for the serving engine (ISSUE 6).

Holds fitted models keyed by model id, loads them from the
topology-portable r10 checkpoints (``utils.checkpoint`` — the
``model_class`` field in every ``_state_dict`` names the family, so a
checkpoint written by ANY mesh/TP layout loads here model-free), and
computes **pack groups**: sets of same-shape K-Means-family models
whose centroid tables can be stacked on a batched model axis (the
``make_multi_fit_fn`` restart-batching idiom applied to inference) so a
routed mixed-model request batch is still ONE dispatch
(``ServingEngine.predict_multi``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kmeans_tpu.utils import checkpoint as ckpt

__all__ = ["ModelRegistry", "model_classes", "load_fitted"]


def model_classes() -> dict:
    """Name -> class map over every servable family (lazy import — the
    registry must not force the whole model zoo at module import)."""
    from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                                   KMeans, MiniBatchKMeans,
                                   SphericalKMeans)
    return {c.__name__: c for c in (KMeans, MiniBatchKMeans,
                                    BisectingKMeans, SphericalKMeans,
                                    GaussianMixture)}


def load_fitted(path):
    """Load a fitted model from a checkpoint, dispatching on the
    ``model_class`` recorded in its metadata (reads ONLY the JSON
    ``__meta__`` member to pick the class — a multi-GB state is not
    materialized twice).  Raises ``ValueError`` naming the class when
    the checkpoint's family is unknown, and the usual
    ``CheckpointCorruptError`` family on torn files."""
    info = ckpt.describe_checkpoint(path)
    if info.get("source") is None:
        # No readable metadata: surface the loader's own corruption
        # error (it names the file and cause).
        ckpt.load_state(path)
        raise ckpt.CheckpointCorruptError(path, "unreadable metadata")
    name = info.get("model_class")
    classes = model_classes()
    if name not in classes:
        raise ValueError(
            f"checkpoint {path} was written by model class {name!r}, "
            f"which this serving build cannot host; known: "
            f"{sorted(classes)}")
    return classes[name].load(path)


class ModelRegistry:
    """Model-id -> fitted-model store with shape-group bookkeeping.

    The registry is pure host-side bookkeeping (ids, specs, pack
    groups); device placement and compiled functions live in the
    engine's ResidentModel wrappers.
    """

    def __init__(self):
        self._models: Dict[str, object] = {}
        self._specs: Dict[str, dict] = {}

    # ------------------------------------------------------------- CRUD

    def register(self, model_id: str, model) -> dict:
        """Add a FITTED model under ``model_id``; returns its serving
        spec (``model.fitted_state()``).  Ids are unique — re-register
        under a new id or ``remove`` first."""
        model_id = str(model_id)
        if model_id in self._models:
            raise ValueError(f"model id {model_id!r} already resident; "
                             f"remove() it first or pick another id")
        spec = model.fitted_state()      # raises if not fitted
        # Serve-and-learn eligibility (ISSUE 20) is a registry-level
        # fact of the model CLASS, recorded once at registration so the
        # engine's learner attach and ``update_status()`` agree on it:
        # in-place online updates require a real incremental path (the
        # MiniBatch Sculley carry), and only the K-Means family has
        # the atomic-swap publication contract.
        spec.setdefault(
            "updatable",
            spec.get("family") == "kmeans"
            and callable(getattr(model, "partial_fit", None)))
        self._models[model_id] = model
        self._specs[model_id] = spec
        return spec

    def load(self, path, model_id: Optional[str] = None
             ) -> Tuple[str, object]:
        """Load a checkpoint into the registry.  ``model_id`` defaults
        to the checkpoint's file stem, suffixed ``-2``, ``-3``, ... on
        collision."""
        model = load_fitted(path)
        if model_id is None:
            from pathlib import Path
            stem = Path(str(path)).stem
            model_id, i = stem, 1
            while model_id in self._models:
                i += 1
                model_id = f"{stem}-{i}"
        self.register(model_id, model)
        return model_id, model

    def get(self, model_id: str):
        try:
            return self._models[model_id]
        except KeyError:
            raise KeyError(
                f"no resident model {model_id!r}; resident: "
                f"{sorted(self._models)}") from None

    def spec(self, model_id: str) -> dict:
        self.get(model_id)
        return self._specs[model_id]

    def remove(self, model_id: str) -> None:
        self.get(model_id)
        del self._models[model_id]
        del self._specs[model_id]

    def ids(self) -> List[str]:
        return sorted(self._models)

    def __contains__(self, model_id) -> bool:
        return model_id in self._models

    def __len__(self) -> int:
        return len(self._models)

    # ------------------------------------------------------ pack groups

    @staticmethod
    def group_key(spec: dict) -> Optional[tuple]:
        """Stacking key: same-(k, D, dtype) K-Means-family models share
        one batched centroid tensor; None for unstackable families
        (GMM: per-component covariance structure has no shared-table
        form)."""
        if not spec.get("stackable"):
            return None
        return (spec["k"], spec["d"], spec["dtype"])

    def pack_groups(self) -> Dict[tuple, List[str]]:
        """All stacking groups with >= 2 members (id order = insertion
        order, which fixes each model's slot on the packed axis)."""
        groups: Dict[tuple, List[str]] = {}
        for model_id, spec in self._specs.items():
            key = self.group_key(spec)
            if key is not None:
                groups.setdefault(key, []).append(model_id)
        return {k: v for k, v in groups.items() if len(v) >= 2}

    def group_ids(self, key: Optional[tuple]) -> List[str]:
        """Resident ids stacking under ``key`` (insertion order — the
        packed-axis slot order); empty for ``key=None``.  Router glue
        (ISSUE 17): fleet placement co-locates a new group member with
        the group's existing home replicas so packed routing
        (``predict_multi``) stays a single dispatch across the fleet."""
        if key is None:
            return []
        return [mid for mid, spec in self._specs.items()
                if self.group_key(spec) == key]
