"""Serve-and-learn actuator: in-place online updates with atomic swap,
snapshot-before-update, and rollback-on-regression (ISSUE 20).

r18 landed the TRIGGER half of ROADMAP item 4 — per-model
:class:`~kmeans_tpu.obs.drift.QualityMonitor` with committed PSI/JS/
score-ratio/near-tie thresholds.  This module is the ACTUATOR half: a
resident MiniBatch-backed model updates in place from sampled live
traffic when its drift monitor fires, Sculley-style, wrapped in the
r10 rotating-checkpoint rollback discipline so a bad update can never
outlive one evaluation window.  Three safety layers:

* **Zero-extra-dispatch reservoir.**  A bounded per-model FIFO of
  traffic blocks, fed ONLY by rows a serving dispatch already
  materialized (the r18 discipline; warmup/verify probes are excluded
  by the engine's ``_tls.warming`` guard).  Draining builds
  fixed-size ``partial_fit`` batches of exactly
  :data:`UPDATE_BATCH_ROWS` rows — one compiled step shape, so after
  the first update every later one is ZERO new compiles (pinned by the
  recompilation sentinel).  Batches are never zero-padded: padding
  rows would enter the Sculley per-center statistics as real mass.
* **Clone-update-swap.**  The update runs ``partial_fit`` on a
  DETACHED working clone (``MiniBatchKMeans._learn_clone``) off the
  dispatch lock — a failed update dies with the clone, the serving
  model bit-identical on last-good.  Publication is ONE atomic swap
  (:func:`publish_tables`): the device table is pre-placed and the
  identity-keyed ``_cents_dev`` cache pre-seeded BEFORE
  ``model.centroids`` is rebound, which is the single publication
  point — ``_cents_dev`` reads ``self.centroids`` exactly once, so a
  concurrent reader sees the old table or the new one, never a torn
  mix (the torn-swap hammer in tests/test_learn.py).
* **Snapshot + rollback.**  Every update snapshots the pre-update
  state via ``utils.checkpoint.save_state_rotating`` first; when the
  post-update windows regress past :data:`REGRESSION_RATIO`, the
  learner restores last-good (``load_state_with_fallback``) and swaps
  back through the same helper, emitting a typed
  :class:`UpdateRolledBack` record.  Update/rollback budgets, debounce
  (via the monitor's committed windows), and cooldown are module
  constants in the ``orchestrator/policy.py`` committed-rules style.

Every decision is recorded three ways: a ``serve.learn`` tracer event,
a ``serve.learn.*`` registry counter, and a JSONL line in the model's
quality sink (``QualityMonitor.record`` — kinds ``update``/
``rollback``, aggregated by the ``serve-status`` multi-file reader).

Headline invariant (pinned by tests/test_learn.py): a QUIESCED
serve-and-learn model is bit-exact equal to the same ``partial_fit``
batch sequence replayed offline from the pre-update snapshot — the
float64 Sculley carry makes the trajectory reproducible — and an
injected update failure or quality regression NEVER fails a serving
request: the model stays on (or returns to) last-good and the engine
keeps serving throughout.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from kmeans_tpu.obs import metrics_registry as _metrics
from kmeans_tpu.obs import trace as _trace
from kmeans_tpu.parallel.mesh import mesh_shape
from kmeans_tpu.utils import checkpoint as ckpt
from kmeans_tpu.utils import faults as _faults

__all__ = [
    "UPDATE_BATCH_ROWS", "UPDATE_MAX_BATCHES", "RESERVOIR_ROWS",
    "UPDATE_MIN_ROWS", "UPDATE_BUDGET", "ROLLBACK_BUDGET",
    "UPDATE_COOLDOWN_WINDOWS", "REGRESSION_RATIO",
    "REGRESSION_EVAL_WINDOWS", "LEARN_P99_EXCURSION_BOUND",
    "COMMITTED_LEARN_RULES",
    "Decision", "UpdateRolledBack", "publish_tables", "ModelLearner",
]

# --------------------------------------------------------- committed rules

#: Rows per ``partial_fit`` update batch.  Committed to the r19 serving
#: bucket ladder's 512 rung — which is ALSO the drift window's row
#: count (``obs.drift.DRIFT_WINDOW_ROWS``), so one update batch carries
#: exactly one window's worth of evidence.  Every update batch has
#: EXACTLY this many rows (never zero-padded — padding rows would
#: corrupt the Sculley per-center counts), so the update step compiles
#: once and every later update is zero new compiles.
UPDATE_BATCH_ROWS = 512

#: Update batches consumed per update step.  Bounds the off-dispatch
#: compute burst of one update (and hence the p99 excursion the
#: BENCH_LEARN row measures) the same way segment sizing bounds a fit
#: dispatch.
UPDATE_MAX_BATCHES = 4

#: Reservoir capacity in rows (trimmed oldest-first at block
#: granularity).  8 full batches: enough to decouple traffic bursts
#: from update cadence, small enough that the retained sample is
#: RECENT — the drifted distribution the update is meant to absorb.
RESERVOIR_ROWS = 8 * UPDATE_BATCH_ROWS

#: Minimum reservoir fill before an update may start: one full batch.
#: An update from less would either pad (forbidden) or train on a
#: different batch shape (a new compile per distinct fill level).
UPDATE_MIN_ROWS = UPDATE_BATCH_ROWS

#: In-place updates a learner may APPLY over its lifetime.  The
#: actuator is a stopgap between refits, not a substitute: a model that
#: needed 8 online updates needs retraining, and an unbounded learner
#: chasing a moving distribution would never say so.
UPDATE_BUDGET = 8

#: Rollbacks before the learner disarms itself.  Two rolled-back
#: updates mean live traffic is not learnable by this loop (regression
#: every time) — continuing would oscillate the serving tables forever.
ROLLBACK_BUDGET = 2

#: Monitor windows between updates (cooldown).  Twice the drift
#: debounce: the post-update evaluation windows must CLOSE before the
#: next update may start, or rollback would have no clean baseline.
UPDATE_COOLDOWN_WINDOWS = 4

#: Post/pre score-per-row ratio above which an applied update is judged
#: a regression and rolled back.  1.25 sits far below the 2.0 drift
#: alert (an update must not merely avoid re-triggering drift — it must
#: not make quality measurably worse than the pre-update serving
#: baseline it was meant to improve).
REGRESSION_RATIO = 1.25

#: Monitor windows that must close after an update before it is judged
#: (same role as the drift debounce: one window is weather).
REGRESSION_EVAL_WINDOWS = 2

#: BENCH_LEARN committed bound: the serving p99 measured DURING an
#: in-place update wave may exceed the quiet-wave p99 by at most this
#: factor.  The update runs off the dispatch lock on a detached clone,
#: so the only serve-path costs are the reservoir copy and the one
#: atomic swap — 3x leaves room for scheduler noise on a busy host
#: while still catching an update that ever re-enters the dispatch
#: path (which would show up as an order-of-magnitude excursion).
LEARN_P99_EXCURSION_BOUND = 3.0

#: The committed serve-and-learn decision table, exported as one dict
#: so tests, ``update_status()``, and the docs pin the SAME numbers.
COMMITTED_LEARN_RULES: Dict[str, float] = {
    "batch_rows": UPDATE_BATCH_ROWS,
    "max_batches": UPDATE_MAX_BATCHES,
    "reservoir_rows": RESERVOIR_ROWS,
    "min_rows": UPDATE_MIN_ROWS,
    "update_budget": UPDATE_BUDGET,
    "rollback_budget": ROLLBACK_BUDGET,
    "cooldown_windows": UPDATE_COOLDOWN_WINDOWS,
    "regression_ratio": REGRESSION_RATIO,
    "eval_windows": REGRESSION_EVAL_WINDOWS,
}

#: Decisions retained in each learner's in-memory log (the
#: ``update_status()`` depth; the JSONL sink keeps everything).
DECISION_HISTORY = 64

#: Registry counter per decision action (the triple-recording
#: contract's counter leg; one fixed name per action, so dashboards
#: never see an unbounded name space).
_ACTION_COUNTERS = {
    "update": "serve.learn.updates",
    "update-failed": "serve.learn.update_failures",
    "update-skipped": "serve.learn.skips",
    "eval-ok": "serve.learn.eval_ok",
    "rollback": "serve.learn.rollbacks",
    "disabled": "serve.learn.disabled",
}


@dataclass
class Decision:
    """One serve-and-learn decision (the autopilot ``Decision``
    discipline applied to serving): what the learner did and why, in
    sequence order."""

    seq: int
    t_s: float
    model: str
    action: str          # a key of _ACTION_COUNTERS
    reason: str
    detail: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"seq": self.seq, "t_s": round(self.t_s, 3),
                "model": self.model, "action": self.action,
                "reason": self.reason, "detail": dict(self.detail)}


@dataclass
class UpdateRolledBack:
    """Typed record of one rollback-to-last-good: which applied update
    regressed, what the committed rule measured, and where the restored
    state came from (``primary`` snapshot or its ``.prev`` rotation)."""

    model: str
    update_seq: int
    reason: str
    pre_ratio: Optional[float]
    post_ratio: Optional[float]
    ratio: Optional[float]
    restored_from: str

    def as_dict(self) -> dict:
        return {"model": self.model, "update_seq": self.update_seq,
                "reason": self.reason, "pre_ratio": self.pre_ratio,
                "post_ratio": self.post_ratio, "ratio": self.ratio,
                "restored_from": self.restored_from}


# ------------------------------------------------------------ atomic swap

def publish_tables(model, mesh, model_shards, *, centroids_f64, seen,
                   iterations_run, sse_history, cluster_sizes=None
                   ) -> float:
    """Publish a new (or restored) centroid table to a LIVE serving
    model through one atomic swap.  The ONLY code in serving/ allowed
    to rebind a resident model's table attributes or touch the
    ``_cents_dev`` identity cache (the ``atomic-swap`` lint rule).

    Why this is torn-proof: ``KMeans._cents_dev`` reads
    ``self.centroids`` exactly ONCE into a local and keys its device
    cache on that array's identity.  Publication therefore orders the
    writes so the ``centroids`` rebind is LAST — the auxiliary f64
    carry/counts first, then the device placement pre-seeded into
    ``_cents_cache`` under the NEW array's identity, then the single
    reference assignment that makes the new table visible.  A reader
    that snapshots ``centroids`` before the rebind serves the old table
    end to end; one that snapshots after serves the new table with its
    placement already warm.  The worst interleaving (a reader placing
    the OLD table between the cache seed and the rebind, overwriting
    the cache entry) costs one redundant re-placement on the next
    dispatch — never a torn read, never a failed request.

    Returns the swap duration in seconds (placement + rebinds — the
    update-pause the BENCH_LEARN row reports)."""
    t0 = time.perf_counter()
    carry = np.asarray(centroids_f64, np.float64)
    new_cents = carry.astype(model.dtype)
    model._centroids_f64 = carry
    model._seen = np.array(seen, dtype=np.float64, copy=True)
    if cluster_sizes is not None:
        model.cluster_sizes_ = np.asarray(cluster_sizes, np.int64)
    model.iterations_run = int(iterations_run)
    model.sse_history = list(sse_history)
    # Pre-place the new table and seed the identity-keyed cache BEFORE
    # the swap: the first post-swap reader must find its device table
    # warm instead of paying a host->device transfer on the dispatch
    # path.
    dev = model._put_centroids(new_cents, mesh, model_shards)
    model._cents_cache = (new_cents, mesh, dev)
    model.centroids = new_cents          # THE swap: old table -> new
    return time.perf_counter() - t0


# One update lock per MODEL OBJECT (not per learner): fleet replicas
# share fitted model objects (one `_cents_dev` placement — ISSUE 17),
# so their per-replica learners must serialize updates on the shared
# model.  Weak-keyed: a removed model's lock dies with it.
_MODEL_LOCKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_MODEL_LOCKS_GUARD = threading.Lock()


def _model_update_lock(model) -> threading.Lock:
    with _MODEL_LOCKS_GUARD:
        lock = _MODEL_LOCKS.get(model)
        if lock is None:
            lock = threading.Lock()
            _MODEL_LOCKS[model] = lock
        return lock


class ModelLearner:
    """The per-(engine, resident model) serve-and-learn loop.

    Lifecycle: the engine feeds ``offer(rows)`` (reservoir) and
    ``poke()`` (trigger check) from its dispatch path — both are cheap
    host-side calls off the compiled path — and the learner runs
    updates/evaluations on a short-lived background thread, never on a
    dispatch thread.  ``update_now(force=True)`` is the synchronous
    path (tests, CLI).  ``close()`` joins any in-flight update before
    the engine closes the model's monitor sink, so an update can never
    write after remove (ISSUE 20 satellite)."""

    def __init__(self, engine, rm, *, snapshot_path: str,
                 batch_rows: int = UPDATE_BATCH_ROWS,
                 max_batches: int = UPDATE_MAX_BATCHES,
                 reservoir_rows: int = RESERVOIR_ROWS,
                 min_rows: int = UPDATE_MIN_ROWS,
                 update_budget: int = UPDATE_BUDGET,
                 rollback_budget: int = ROLLBACK_BUDGET,
                 cooldown_windows: int = UPDATE_COOLDOWN_WINDOWS,
                 regression_ratio: float = REGRESSION_RATIO,
                 eval_windows: int = REGRESSION_EVAL_WINDOWS):
        self.engine = engine
        self.rm = rm
        self.model = rm.model
        self.model_id = rm.model_id
        self.monitor = rm.monitor
        if self.monitor is None:
            raise ValueError(
                f"model {rm.model_id!r} has no quality monitor; the "
                f"serve-and-learn trigger IS the drift monitor — serve "
                f"with quality monitoring on to learn")
        self.snapshot_path = str(snapshot_path)
        self.batch_rows = int(batch_rows)
        self.max_batches = int(max_batches)
        self.reservoir_rows = int(reservoir_rows)
        self.min_rows = max(int(min_rows), self.batch_rows)
        self.update_budget = int(update_budget)
        self.rollback_budget = int(rollback_budget)
        self.cooldown_windows = int(cooldown_windows)
        self.regression_ratio = float(regression_ratio)
        self.eval_windows = int(eval_windows)

        self._res: deque = deque()
        self._res_rows = 0
        self._res_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._busy = threading.Lock()        # one in-flight worker
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._armed = True
        self._seq = 0
        self._t0 = time.monotonic()
        self._last_update_window = -self.cooldown_windows
        self._pending: Optional[dict] = None
        self.updates_applied = 0
        self.updates_failed = 0
        self.rollbacks: List[UpdateRolledBack] = []
        self.decisions: deque = deque(maxlen=DECISION_HISTORY)
        # Batches each APPLIED update consumed, newest last (the
        # quiesced-equivalence tests replay these offline; bounded like
        # the decision log).
        self.applied_batches: deque = deque(maxlen=DECISION_HISTORY)

    # -------------------------------------------------------- reservoir

    def offer(self, rows: np.ndarray) -> None:
        """Retain one dispatch's ALREADY-MATERIALIZED rows (a copy —
        the dispatch buffer is sliced per request by the queue).
        Oldest blocks fall off when the cap is exceeded (block
        granularity: the cap bounds retention, not batch shapes)."""
        if self._closed or not self._armed:
            return
        block = np.array(rows, copy=True)
        if block.ndim != 2 or block.shape[0] == 0:
            return
        with self._res_lock:
            self._res.append(block)
            self._res_rows += block.shape[0]
            while self._res_rows - self._res[0].shape[0] \
                    >= self.reservoir_rows:
                self._res_rows -= self._res.popleft().shape[0]

    def _drain_batches(self) -> List[np.ndarray]:
        """Pop the oldest ``n * batch_rows`` reservoir rows as exact
        fixed-size batches (FIFO — arrival order, so the offline
        replay of the same traffic reconstructs the same batches)."""
        with self._res_lock:
            n_batches = min(self._res_rows // self.batch_rows,
                            self.max_batches)
            if n_batches == 0:
                return []
            need = n_batches * self.batch_rows
            taken, got = [], 0
            while got < need:
                block = self._res.popleft()
                take = min(block.shape[0], need - got)
                taken.append(block[:take])
                if take < block.shape[0]:
                    self._res.appendleft(block[take:])
                got += take
            self._res_rows -= need
        rows = np.concatenate(taken, axis=0)
        B = self.batch_rows
        return [np.ascontiguousarray(rows[i * B:(i + 1) * B])
                for i in range(n_batches)]

    # -------------------------------------------------------- recording

    def _decide(self, action: str, reason: str, **detail) -> Decision:
        """Record one decision THREE ways (the ISSUE 20 contract):
        tracer event + registry counter + JSONL line in the model's
        quality sink."""
        with self._state_lock:
            self._seq += 1
            d = Decision(seq=self._seq,
                         t_s=time.monotonic() - self._t0,
                         model=self.model_id, action=action,
                         reason=reason, detail=detail)
            self.decisions.append(d)
        _metrics.REGISTRY.counter(_ACTION_COUNTERS[action]).inc()
        _trace.event("serve.learn", model=self.model_id, action=action,
                     reason=reason)
        if not self._closed:
            kind = "rollback" if action == "rollback" else "update"
            sink_action = {"update": "applied",
                           "update-failed": "failed",
                           "update-skipped": "skipped",
                           "eval-ok": "eval-ok",
                           "rollback": "rollback",
                           "disabled": "disabled"}[action]
            self.monitor.record(kind, action=sink_action, seq=d.seq,
                                reason=reason, **detail)
        return d

    # ---------------------------------------------------------- trigger

    def _update_due(self) -> bool:
        if not self._armed or self._closed or self._pending is not None:
            return False
        if self.updates_applied >= self.update_budget:
            return False
        if self._res_rows < self.min_rows:
            return False
        if not self.monitor.drifting:
            return False
        return (self.monitor.windows - self._last_update_window
                >= self.cooldown_windows)

    def _eval_due(self) -> bool:
        p = self._pending
        return (p is not None
                and self.monitor.windows >= p["eval_after_window"])

    def poke(self) -> None:
        """Cheap post-dispatch trigger check; spawns the background
        worker when an update or a pending evaluation is due.  Called
        by the engine after every quality feed — must stay O(1) reads
        on the common path."""
        if self._closed or not self._armed or self._busy.locked():
            return
        if not (self._eval_due() or self._update_due()):
            return
        if not self._busy.acquire(blocking=False):
            return
        try:
            # lint: ok(thread) — joined at close(): the handle is kept
            # on self._thread and ModelLearner.close() joins it before
            # the engine tears down the model's sinks
            t = threading.Thread(target=self._worker,
                                 name=f"learn-{self.model_id}",
                                 daemon=True)
            self._thread = t
            t.start()
        except BaseException:
            self._busy.release()
            raise

    def _worker(self) -> None:
        try:
            if self._eval_due():
                self._evaluate()
            elif self._update_due():
                self._run_update(force=False, reason="drift")
        except Exception as e:  # noqa: BLE001 — actuator isolation:
            # a learner bug must never take the serving engine down.
            self._decide("update-failed", f"internal: {e}",
                         error=type(e).__name__, ok=False)
        finally:
            self._busy.release()

    # ----------------------------------------------------------- update

    def evaluate_now(self, *, force: bool = True) -> None:
        """Synchronously judge the pending update (test / CLI path);
        ``force=True`` judges on whatever windows exist instead of
        waiting out the committed evaluation debounce."""
        with self._busy:
            self._evaluate(force=force)

    def update_now(self, *, force: bool = True,
                   reason: str = "manual") -> Optional[dict]:
        """Synchronous update (the test / CLI path): runs any due
        evaluation first, then one update step on the CALLING thread.
        ``force=True`` bypasses the drift trigger and cooldown (never
        the budgets or the min-fill rule).  Returns the update
        decision's dict (None when nothing ran)."""
        with self._busy:
            if self._pending is not None:
                self._evaluate(force=force)
            d = self._run_update(force=force, reason=reason)
        return d.as_dict() if d is not None else None

    def _run_update(self, *, force: bool,
                    reason: str) -> Optional[Decision]:
        """One update step.  Caller holds ``_busy``."""
        if self._closed or not self._armed:
            return None
        if self.updates_applied >= self.update_budget:
            return self._decide("update-skipped", "update-budget-exhausted",
                                budget=self.update_budget, ok=False)
        if not force and not self._update_due():
            return None
        mlock = _model_update_lock(self.model)
        if not mlock.acquire(blocking=False):
            # A fleet peer's learner is updating the SHARED model.
            return self._decide("update-skipped", "peer-updating",
                                ok=False)
        try:
            return self._run_update_locked(reason)
        finally:
            mlock.release()

    def _run_update_locked(self, reason: str) -> Optional[Decision]:
        batches = self._drain_batches()
        if not batches:
            return self._decide("update-skipped", "reservoir-underfilled",
                                rows=self._res_rows,
                                min_rows=self.min_rows, ok=False)
        # Pre-update baseline for the regression rule, measured BEFORE
        # anything changes: the recent informative windows' score
        # ratio under the OLD table.
        pre_ratio = self._recent_score_ratio(after_window=None)
        pre_sizes = np.array(self.model.cluster_sizes_, copy=True) \
            if getattr(self.model, "cluster_sizes_", None) is not None \
            else None
        # 1. Snapshot-before-update (rotating: the previous snapshot
        #    survives at .prev, so even a torn snapshot write leaves a
        #    restorable last-good).
        try:
            ckpt.save_state_rotating(self.snapshot_path,
                                     self.model._state_dict())
        except Exception as e:  # noqa: BLE001 — typed by record
            self.updates_failed += 1
            return self._decide("update-failed", f"snapshot: {e}",
                                error=type(e).__name__, ok=False)
        # 2. partial_fit on a detached clone, OFF the dispatch lock —
        #    the serving model is untouched until the swap.
        t_fit = time.perf_counter()
        try:
            clone = self.model._learn_clone()
            for i, batch in enumerate(batches):
                _faults.on_update_step(self.model_id, i)
                clone.partial_fit(batch)
        except Exception as e:  # noqa: BLE001 — any failure here
            # leaves the serving model bit-identical on last-good.
            self.updates_failed += 1
            # Cooldown anyway: a deterministic failure must not retry
            # in a hot loop on every window close.
            self._last_update_window = self.monitor.windows
            return self._decide("update-failed", str(e),
                                error=type(e).__name__,
                                n_batches=len(batches), ok=False)
        fit_s = time.perf_counter() - t_fit
        if self._closed:
            # remove()/close() raced the update: the model (and its
            # sinks) may already be torn down — never publish.
            return None
        # 3. ONE atomic swap publishes the clone's tables.
        swap_s = publish_tables(
            self.model, self.engine.mesh,
            mesh_shape(self.engine.mesh)[1],
            centroids_f64=clone._centroids_f64,
            seen=clone._seen,
            cluster_sizes=clone.cluster_sizes_,
            iterations_run=clone.iterations_run,
            sse_history=clone.sse_history)
        self.updates_applied += 1
        self._last_update_window = self.monitor.windows
        self.applied_batches.append(batches)
        self._pending = {
            "update_seq": self._seq + 1,
            "window": self.monitor.windows,
            "eval_after_window": self.monitor.windows + self.eval_windows,
            "pre_ratio": pre_ratio,
            "pre_cluster_sizes": pre_sizes,
        }
        return self._decide(
            "update", reason, ok=True, n_batches=len(batches),
            rows=len(batches) * self.batch_rows,
            fit_ms=round(fit_s * 1e3, 3),
            swap_ms=round(swap_s * 1e3, 3),
            budget_left=self.update_budget - self.updates_applied,
            snapshot=self.snapshot_path)

    # ------------------------------------------------------- evaluation

    def _recent_score_ratio(self, *, after_window: Optional[int]
                            ) -> Optional[float]:
        """Median ``score_ratio`` over the newest informative windows
        (at most ``eval_windows`` of them), optionally restricted to
        windows closed AFTER ``after_window``.  None when no window
        carried a score reading."""
        vals = [w["detectors"].get("score_ratio")
                for w in self.monitor.history()
                if (after_window is None or w["window"] > after_window)]
        vals = [v for v in vals if v is not None]
        if not vals:
            return None
        return float(np.median(vals[-self.eval_windows:]))

    def _evaluate(self, *, force: bool = False) -> None:
        """Judge the pending update against the committed regression
        rule; roll back to the snapshot on breach.  Caller holds
        ``_busy``."""
        p = self._pending
        if p is None or self._closed:
            return
        if not force and not self._eval_due():
            return
        post = self._recent_score_ratio(after_window=p["window"])
        pre = p["pre_ratio"]
        ratio = (post / pre) if (post is not None and pre) else None
        # Injection point (utils.faults.inject_quality_regression):
        # armed hooks may override the measured ratio, driving the
        # rollback branch through the real restore + swap path.
        ratio = _faults.on_update_eval(self.model_id, ratio)
        self._pending = None
        if ratio is None or ratio <= self.regression_ratio:
            self._decide("eval-ok",
                         "no-score-signal" if ratio is None
                         else "within-threshold",
                         update_seq=p["update_seq"],
                         pre_ratio=pre, post_ratio=post, ratio=ratio,
                         ok=True)
            return
        self._rollback(p, pre=pre, post=post, ratio=ratio)

    def _rollback(self, pending: dict, *, pre, post, ratio) -> None:
        """Restore the pre-update snapshot and swap it back in —
        the same atomic publication as the update itself."""
        try:
            state, used_fallback = ckpt.load_state_with_fallback(
                self.snapshot_path)
        except Exception as e:  # noqa: BLE001 — both rotations torn:
            # record loudly, disarm; the model keeps serving the
            # (regressed but functional) updated table — a failed
            # restore must never take serving down.
            self._armed = False
            self._decide("disabled", f"rollback-restore-failed: {e}",
                         error=type(e).__name__, ok=False)
            return
        carry = state.get("centroids_f64")
        if carry is None:
            carry = np.asarray(state["centroids"], np.float64)
        if self._closed:
            return
        swap_s = publish_tables(
            self.model, self.engine.mesh,
            mesh_shape(self.engine.mesh)[1],
            centroids_f64=carry,
            seen=state["seen_counts"],
            cluster_sizes=pending.get("pre_cluster_sizes"),
            iterations_run=int(state["iterations_run"]),
            sse_history=list(state["sse_history"]))
        restored_from = "prev" if used_fallback else "primary"
        rec = UpdateRolledBack(
            model=self.model_id, update_seq=pending["update_seq"],
            reason=f"score regression {ratio:.3f} > "
                   f"{self.regression_ratio} over {self.eval_windows} "
                   f"windows",
            pre_ratio=pre, post_ratio=post, ratio=float(ratio),
            restored_from=restored_from)
        self.rollbacks.append(rec)
        self._last_update_window = self.monitor.windows
        self._decide("rollback", rec.reason, ok=True,
                     update_seq=pending["update_seq"],
                     pre_ratio=pre, post_ratio=post, ratio=float(ratio),
                     restored_from=restored_from,
                     swap_ms=round(swap_s * 1e3, 3))
        if len(self.rollbacks) >= self.rollback_budget:
            self._armed = False
            self._decide("disabled", "rollback-budget-exhausted",
                         rollbacks=len(self.rollbacks),
                         budget=self.rollback_budget, ok=False)

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        """The ``update_status()`` payload for this model: armed state,
        budgets, reservoir fill, pending evaluation, and the recent
        decision log."""
        with self._state_lock:
            p = self._pending
            return {
                "model": self.model_id,
                "armed": self._armed and not self._closed,
                "closed": self._closed,
                "updates_applied": self.updates_applied,
                "updates_failed": self.updates_failed,
                "rollbacks": [r.as_dict() for r in self.rollbacks],
                "update_budget_left":
                    max(self.update_budget - self.updates_applied, 0),
                "rollback_budget_left":
                    max(self.rollback_budget - len(self.rollbacks), 0),
                "reservoir_rows": self._res_rows,
                "pending_eval": ({
                    "update_seq": p["update_seq"],
                    "eval_after_window": p["eval_after_window"],
                    "pre_ratio": p["pre_ratio"],
                } if p is not None else None),
                "snapshot": self.snapshot_path,
                "rules": {
                    "batch_rows": self.batch_rows,
                    "max_batches": self.max_batches,
                    "reservoir_rows": self.reservoir_rows,
                    "min_rows": self.min_rows,
                    "update_budget": self.update_budget,
                    "rollback_budget": self.rollback_budget,
                    "cooldown_windows": self.cooldown_windows,
                    "regression_ratio": self.regression_ratio,
                    "eval_windows": self.eval_windows,
                },
                "decisions": [d.as_dict() for d in self.decisions],
            }

    # --------------------------------------------------------- lifecycle

    def close(self, *, join: bool = True) -> None:
        """Stop learning and JOIN any in-flight update before the
        caller tears down the model's sinks — an update thread must
        never publish to a removed model or write to a closed sink
        (ISSUE 20 satellite: the remove()-vs-update race).
        Idempotent."""
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
        t = self._thread
        if join and t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=60.0)
        with self._res_lock:
            self._res.clear()
            self._res_rows = 0


def snapshot_path_for(learn_dir: str, model_id: str,
                      tag: Optional[str] = None) -> str:
    """The rotating pre-update snapshot path for one (model, replica):
    ``learn.<model_id>[.<tag>].npz`` next to the quality sinks, so the
    whole serve-and-learn paper trail of a model lives in one
    directory."""
    name = f"learn.{model_id}.npz" if tag is None \
        else f"learn.{model_id}.{tag}.npz"
    return os.path.join(learn_dir, name)
