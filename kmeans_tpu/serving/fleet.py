"""Multi-tenant serving fleet: replicated engines behind an SLO-aware
router (ISSUE 17 tentpole).

One :class:`~kmeans_tpu.serving.engine.ServingEngine` on one mesh was
the serving ceiling; production traffic wants N replicas behind a
router (ROADMAP item 5: "traffic scale, not just dispatch speed").
:class:`ServingFleet` composes the existing parts into that tier:

* **Replicated engines.**  N engine replicas over one process's mesh
  (the CPU/CI form the tests pin; a multi-host deployment runs one
  fleet worker per host and aggregates through the per-replica sinks
  below).  Replicas share the fitted model OBJECTS, so the ``_cents_dev``
  placement caches and the ``_STEP_CACHE`` compiled programs are shared
  — replication costs bookkeeping, not recompiles, and fleet labels are
  bit-equal to a single engine's by construction
  (tests/test_fleet_serving.py pins every dispatch path).
* **SLO-aware routing.**  The router keeps per-(replica, model, bucket)
  latency histograms in the r18 metrics registry
  (``fleet.latency_ms.<replica>.<model>.b<bucket>``) fed with every
  routed request's measured latency, and routes each request to the
  replica with the LEAST EXPECTED LATENCY — ``(inflight + 1) * p50`` —
  once every candidate's histogram is warm (``MIN_ROUTE_SAMPLES``
  observations).  While any candidate is cold it falls back to a
  deterministic power-of-two-choices rule: two candidates from a
  rotating counter, fewer in-flight requests wins (ties -> lower
  replica index) — deterministic so the shed/routing tests need no RNG
  seeds.
* **Admission control + load shedding.**  With a committed p99 bound
  (``slo_p99_ms``) the router sheds a request when every candidate's
  expected completion ``(inflight + 1) * p99`` would breach the bound
  (cold candidates admit — shedding is never justified without data),
  and with ``max_inflight`` when every candidate is at the depth limit.
  A shed is EXPLICIT: :class:`FleetOverloadError` to the caller,
  ``fleet.shed`` / ``fleet.shed.<model>`` counters in the registry —
  never a silent drop (the ``fleet-record`` lint rule statically
  requires every forward/shed site to record).
* **Pack-group-aware placement.**  With partial replication
  (``replication < n_replicas``) a model lands on the least-loaded
  replicas, EXCEPT that members of an existing pack group
  (same-(k, D, dtype), r11) co-reside with their group so
  ``predict_multi`` stays one packed dispatch fleet-wide.
* **Replica lifecycle.**  A replica takes traffic only in state
  ``'serving'`` — reached through ``warmup()``, which pre-compiles the
  bucket shapes (under an active r19 AOT store this loads executables
  from the shared ``<ckpt>.aot`` mirror instead of compiling, so
  ``add_replica`` on a warm cache is near-free — the BENCH_FLEET
  prewarm row).  Each replica appends heartbeat records
  (``hb.<replica>.jsonl``, r17 schema) to the fleet directory;
  ``fleet-status`` renders them per replica, and :meth:`reap` declares
  a replica dead when it holds in-flight work but has not completed a
  dispatch within the stall window (``DEAD_AFTER_FACTOR`` heartbeat
  intervals, min ``DEAD_MIN_S``).  A dead (or chaos-killed) replica's
  queued requests fail through the engine ``dispatch_guard`` ->
  micro-batch queue per-member isolation, and the router re-dispatches
  each one on a surviving replica (``fleet.redispatch`` counter) — the
  kill-a-replica chaos run pins zero failed requests.

Sinks: ``fleet_dir`` holds per-replica quality sinks
(``quality.<model>.<replica>.jsonl`` — the engine's ``quality_tag``
glue) and heartbeats; ``serve-status <dir>`` merges drift state per
model across replicas, ``fleet-status <dir>`` shows per-replica
liveness — both existing multi-file readers, unchanged exit codes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.serving.batching import (DEFAULT_BUCKETS, ServingFuture,
                                         bucket_for, check_buckets)
from kmeans_tpu.serving.engine import ServingEngine
from kmeans_tpu.serving.registry import ModelRegistry, load_fitted

__all__ = ["ServingFleet", "FleetFuture", "FleetOverloadError",
           "ReplicaDeadError", "MIN_ROUTE_SAMPLES", "DEAD_AFTER_FACTOR",
           "DEAD_MIN_S"]

#: Histogram observations before a (replica, model, bucket) latency
#: estimate is trusted for least-expected-latency routing; below this
#: the router uses the deterministic power-of-two-choices fallback.
MIN_ROUTE_SAMPLES = 8

#: Routed requests between percentile refreshes per (replica, model,
#: bucket).  ``Histogram.percentile`` sorts its reservoir (<= 512
#: samples) on every call; recomputing p50/p99 per routed request made
#: the router's hot path O(reservoir log reservoir) and dominated the
#: measured BENCH_FLEET overhead on sub-ms CPU dispatches.  Routing on
#: estimates up to 32 observations stale is harmless — the queue-depth
#: term ``(inflight + 1)`` tracks the fast signal; percentiles are the
#: slow one.
ROUTE_REFRESH = 32

#: A replica holding in-flight work with no completed dispatch for
#: ``DEAD_AFTER_FACTOR`` heartbeat intervals (but at least
#: ``DEAD_MIN_S`` seconds) is declared dead by :meth:`ServingFleet.reap`
#: — the straggler-stall rule (obs.fleet) applied to serving liveness.
DEAD_AFTER_FACTOR = 3.0
DEAD_MIN_S = 1.0


class FleetOverloadError(RuntimeError):
    """The explicit shed response (ISSUE 17 admission control): the
    committed p99 bound (or the in-flight depth limit) would be
    breached on every candidate replica, so the request is REFUSED
    up front rather than queued into a bound violation.  Counted in
    the registry (``fleet.shed`` / ``fleet.shed.<model>``) — never a
    silent drop."""


class ReplicaDeadError(RuntimeError):
    """A dispatch was refused because its target replica is dead
    (killed by chaos injection or reaped on heartbeat stall).  Raised
    by the engine's ``dispatch_guard``; the router catches it and
    re-dispatches the request on a surviving replica."""


class _Replica:
    """One engine replica: the engine + router-side state (liveness,
    in-flight count, heartbeat sink)."""

    def __init__(self, name: str, index: int, engine: ServingEngine,
                 hb_path: Optional[str], hb_interval_s: float):
        self.name = name
        self.index = index
        self.engine = engine
        self.state = "warming"            # 'warming' | 'serving' | 'dead'
        self.killed = False
        self.inflight = 0
        self.models: set = set()
        self.prewarm_s: Optional[float] = None
        # Chaos injection point (utils.faults.inject_replica_kill):
        # called with (replica, model_id, op) before the killed check.
        self.fault_hook = None
        # Router-clock time of the last COMPLETED dispatch (the reap
        # signal); wall-clock bookkeeping for the heartbeat sink.
        self.last_beat: Optional[float] = None
        self._hb_path = hb_path
        self._hb_interval = float(hb_interval_s)
        self._hb_wall_last: Optional[float] = None
        self._hb_rows = 0
        engine.dispatch_guard = self._guard

    def _guard(self, model_id, op: str) -> None:
        """Engine pre-dispatch hook: chaos first, then liveness — a
        killed replica refuses EVERY dispatch (direct, queued batch,
        packed), so queued requests fail through the micro-batch
        queue's per-member isolation and the router re-dispatches
        them."""
        hook = self.fault_hook
        if hook is not None:
            hook(self, model_id, op)
        if self.killed:
            raise ReplicaDeadError(
                f"replica {self.name!r} is dead (dispatch refused)")

    def beat(self, *, rows: int = 0, force: bool = False) -> None:
        """Append one heartbeat record (r17 schema: ``ts`` + identity +
        progress) to this replica's sink, rate-limited to the fleet's
        heartbeat interval.  ``iteration`` carries the engine dispatch
        count and ``rows_per_sec`` the recent serving throughput, so
        ``fleet-status`` renders progress and liveness per replica."""
        self._hb_rows += rows
        if self._hb_path is None:
            return
        now = time.time()
        if not force and self._hb_wall_last is not None \
                and now - self._hb_wall_last < self._hb_interval:
            return
        rate = None
        if self._hb_wall_last is not None and now > self._hb_wall_last:
            rate = self._hb_rows / (now - self._hb_wall_last)
        rec = {"ts": now, "phase": "serving",
               "iteration": int(self.engine.dispatches),
               "rows_per_sec": rate, "process_index": self.index,
               "host": self.name, "replica": self.name,
               "state": self.state, "inflight": int(self.inflight)}
        import json
        try:
            with open(self._hb_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            # Telemetry must never fail serving; the sink simply goes
            # stale and fleet-status reports the age.
            pass
        self._hb_wall_last = now
        self._hb_rows = 0


class FleetFuture:
    """Completion handle for one fleet-routed queued request.

    ``result()`` returns the request's own rows' slice (the
    :class:`ServingFuture` contract).  If the target replica died with
    the request in flight, the failure surfaces here as
    :class:`ReplicaDeadError` from the queue's isolation machinery and
    the future transparently re-dispatches on a surviving replica —
    the caller sees a result, never the dead replica."""

    def __init__(self, fleet: "ServingFleet", rep: _Replica,
                 inner: ServingFuture, model_id, rows, op: str,
                 t0: float):
        self._fleet = fleet
        self._rep = rep
        self._inner = inner
        self._model_id = model_id
        self._rows = rows
        self._op = op
        self._t0 = t0
        self._settled = False

    def done(self) -> bool:
        return self._inner.done()

    def result(self, timeout: Optional[float] = None):
        while True:
            try:
                out = self._inner.result(timeout)
            except ReplicaDeadError:
                self._fleet._fail_over(self._rep)
                rep, inner = self._fleet._resubmit(
                    self._model_id, self._rows, self._op)
                self._rep, self._inner = rep, inner
                continue
            except Exception:
                self._settle(error=True)
                raise
            self._settle()
            return out

    def exception(self, timeout: Optional[float] = None):
        try:
            self.result(timeout)
            return None
        except TimeoutError:
            raise
        except Exception as e:              # noqa: BLE001 — mirror
            return e                        # ServingFuture.exception

    def _settle(self, error: bool = False) -> None:
        """Release the in-flight slot and (on success) feed the routing
        histogram — once, however many times result() is called."""
        if self._settled:
            return
        self._settled = True
        self._fleet._complete(self._rep, self._model_id,
                              self._rows, self._t0, error=error)


class ServingFleet:
    """N :class:`ServingEngine` replicas behind an SLO-aware router.

    Parameters
    ----------
    n_replicas : initial replica count (``add_replica``/``kill_replica``
        /``remove_replica`` grow and shrink it later).
    mesh, buckets, max_wait_ms, clock, start, quality, quality_window :
        forwarded to every replica engine (one shared mesh: in-process
        replicas serve the same devices, so compiled programs and
        placements are shared and parity with a single engine is by
        construction).  ``clock`` also drives the router's latency
        observations and the :meth:`reap` liveness rule — injectable
        for deterministic shed tests.
    fleet_dir : directory for per-replica sinks — quality JSONL
        (``quality.<model>.<replica>.jsonl``) and heartbeats
        (``hb.<replica>.jsonl``); the ``serve-status`` /
        ``fleet-status`` input.  None = in-memory only.
    slo_p99_ms : committed p99 latency bound (ms).  None disables
        admission control (route-only fleet).
    max_inflight : per-replica in-flight depth limit (admission sheds
        when EVERY candidate is at the limit).  None = unbounded.
    replication : copies of each model across the fleet (placement is
        least-loaded, pack-group co-resident).  None = full
        replication on every replica.
    heartbeat_interval_s : min seconds between heartbeat records (and
        the base of the :meth:`reap` stall window).
    learn : False | True | dict — forwarded to every replica engine
        (ISSUE 20 serve-and-learn).  Replicas share the fitted model
        OBJECTS, so their per-replica learners serialize updates on a
        per-model lock (``serving.learn._model_update_lock``) and every
        replica serves the swapped table the instant it publishes;
        snapshots stay per-replica via the ``quality_tag`` filename
        glue.  :meth:`update_status` aggregates the per-replica
        learner state.
    """

    def __init__(self, n_replicas: int = 2, *, mesh=None,
                 buckets=DEFAULT_BUCKETS, max_wait_ms: float = 2.0,
                 clock=None, start: bool = True, quality="auto",
                 quality_window: Optional[int] = None,
                 fleet_dir=None, slo_p99_ms: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 replication: Optional[int] = None,
                 heartbeat_interval_s: float = 0.5,
                 learn=False):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if replication is not None and replication < 1:
            raise ValueError(f"replication must be >= 1, "
                             f"got {replication}")
        self.mesh = mesh if mesh is not None else make_mesh()
        self.buckets = check_buckets(buckets)
        self._max_wait_ms = float(max_wait_ms)
        self._clock = clock if clock is not None else time.monotonic
        self._user_clock = clock
        self._start = bool(start)
        self._quality = quality
        self._quality_window = quality_window
        self._fleet_dir = str(fleet_dir) if fleet_dir is not None else None
        if self._fleet_dir is not None:
            os.makedirs(self._fleet_dir, exist_ok=True)
        self.slo_p99_ms = float(slo_p99_ms) if slo_p99_ms is not None \
            else None
        self.max_inflight = int(max_inflight) if max_inflight is not None \
            else None
        self._replication = int(replication) if replication is not None \
            else None
        self._hb_interval = float(heartbeat_interval_s)
        self._learn = learn
        self.registry = ModelRegistry()     # fleet-level placement view
        self._quantize: Dict[str, Optional[str]] = {}
        self._profiles: Dict[str, Optional[dict]] = {}
        self._placement: Dict[str, List[int]] = {}
        self._group_homes: Dict[tuple, List[int]] = {}
        self._replicas: List[_Replica] = []
        self._hists: Dict[tuple, object] = {}
        self._est: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._rr = 0                        # power-of-two rotation
        self._next_index = 0
        self.routes = 0
        self.sheds = 0
        self.redispatches = 0
        self._closed = False
        for _ in range(int(n_replicas)):
            self._spawn()

    # -------------------------------------------------------- replicas

    def _spawn(self) -> _Replica:
        i = self._next_index
        self._next_index += 1
        name = f"r{i}"
        eng = ServingEngine(
            mesh=self.mesh, buckets=self.buckets,
            max_wait_ms=self._max_wait_ms, clock=self._user_clock,
            start=self._start, quality=self._quality,
            quality_dir=self._fleet_dir,
            quality_window=self._quality_window, quality_tag=name,
            learn=self._learn)
        hb = os.path.join(self._fleet_dir, f"hb.{name}.jsonl") \
            if self._fleet_dir is not None else None
        rep = _Replica(name, i, eng, hb, self._hb_interval)
        self._replicas.append(rep)
        return rep

    def _replica(self, name) -> _Replica:
        for rep in self._replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica {name!r}; fleet: "
                       f"{[r.name for r in self._replicas]}")

    def replicas(self) -> List[str]:
        return [r.name for r in self._replicas]

    def add_replica(self, *, prewarm: bool = True) -> str:
        """Grow the fleet by one replica.  Fully-replicated models are
        placed on it immediately; with partial replication it joins the
        placement pool for future models.  With ``prewarm`` the replica
        compiles (or AOT-loads, r19) every bucket shape BEFORE entering
        ``'serving'`` — it never takes traffic cold; ``prewarm_s``
        (stats) is the measured cost, the BENCH_FLEET prewarm row."""
        rep = self._spawn()
        if self._replication is None:
            for mid in self.registry.ids():
                rep.engine.add_model(mid, self.registry.get(mid),
                                     quantize=self._quantize[mid],
                                     profile=self._profiles[mid])
                rep.models.add(mid)
                self._placement[mid].append(rep.index)
        t0 = time.perf_counter()
        self._warm_replica(rep, prewarm=prewarm)
        rep.prewarm_s = time.perf_counter() - t0
        return rep.name

    def kill_replica(self, name) -> None:
        """Chaos kill (``utils.faults`` discipline): the replica
        refuses every further dispatch via the engine guard, so its
        queued in-flight requests fail through the queue's per-member
        isolation and re-dispatch on survivors.  Routing skips it
        immediately."""
        rep = self._replica(name)
        rep.killed = True
        rep.state = "dead"

    def remove_replica(self, name) -> None:
        """Graceful shrink: stop routing to the replica, drain its
        queue (pending requests still complete — it is not killed),
        and release its models from the placement map."""
        rep = self._replica(name)
        rep.state = "dead"
        rep.engine.close()
        for mid in list(rep.models):
            idxs = self._placement.get(mid, [])
            if rep.index in idxs:
                idxs.remove(rep.index)
        for key, homes in list(self._group_homes.items()):
            if rep.index in homes:
                homes.remove(rep.index)

    def _fail_over(self, rep: _Replica) -> None:
        """Mark a replica dead after a ReplicaDeadError surfaced from
        its dispatch path, and count the re-dispatch that follows."""
        rep.killed = True
        rep.state = "dead"
        with self._lock:
            self.redispatches += 1
        obs_metrics.REGISTRY.counter("fleet.redispatch").inc()

    def reap(self, now: Optional[float] = None) -> List[str]:
        """Heartbeat-driven death detection: declare dead every serving
        replica that HOLDS in-flight work but has not completed a
        dispatch within the stall window (``DEAD_AFTER_FACTOR``
        heartbeat intervals, min ``DEAD_MIN_S`` — the obs.fleet
        straggler-stall rule applied to serving).  An idle replica
        never reaps: no outstanding work means no evidence of death.
        Returns the newly dead replica names; their queued requests
        fail over on the next result() collection."""
        now = self._clock() if now is None else now
        window = max(DEAD_AFTER_FACTOR * self._hb_interval, DEAD_MIN_S)
        newly: List[str] = []
        for rep in self._replicas:
            if rep.state != "serving" or rep.inflight <= 0:
                continue
            if rep.last_beat is not None \
                    and now - rep.last_beat > window:
                rep.killed = True
                rep.state = "dead"
                newly.append(rep.name)
        return newly

    # ------------------------------------------------------- residency

    def add_model(self, model_id: str, model, *,
                  quantize: Optional[str] = None,
                  profile: Optional[dict] = None) -> List[str]:
        """Make a fitted model resident across the fleet; returns the
        replica names it was placed on (pack-group co-resident,
        least-loaded — module docstring)."""
        spec = self.registry.register(model_id, model)
        idxs = self._place(spec)
        placed: List[int] = []
        try:
            for i in idxs:
                rep = self._replicas[i]
                rep.engine.add_model(model_id, model, quantize=quantize,
                                     profile=profile)
                rep.models.add(model_id)
                placed.append(i)
        except Exception:
            for i in placed:
                self._replicas[i].engine.remove(model_id)
                self._replicas[i].models.discard(model_id)
            self.registry.remove(model_id)
            raise
        self._placement[model_id] = list(idxs)
        self._quantize[model_id] = quantize
        self._profiles[model_id] = profile
        key = ModelRegistry.group_key(spec)
        if key is not None and key not in self._group_homes:
            self._group_homes[key] = list(idxs)
        return [self._replicas[i].name for i in idxs]

    def load(self, path, model_id: Optional[str] = None, *,
             quantize: Optional[str] = None) -> str:
        """Load a topology-portable checkpoint once and place it across
        the fleet (every replica shares the one fitted model object —
        one host copy, one device placement)."""
        model = load_fitted(path)
        if model_id is None:
            from pathlib import Path
            stem = Path(str(path)).stem
            model_id, i = stem, 1
            while model_id in self.registry:
                i += 1
                model_id = f"{stem}-{i}"
        self.add_model(model_id, model, quantize=quantize)
        return model_id

    def models(self) -> List[str]:
        return self.registry.ids()

    def _place(self, spec: dict) -> List[int]:
        """Home replica indices for a new model: the pack group's
        existing homes when one exists (co-residency keeps packed
        routing alive), else the ``replication`` least-loaded live
        replicas (ties -> lower index)."""
        live = [r for r in self._replicas if r.state != "dead"]
        if not live:
            raise RuntimeError("fleet has no live replicas")
        key = ModelRegistry.group_key(spec)
        if key is not None:
            homes = [i for i in self._group_homes.get(key, [])
                     if self._replicas[i].state != "dead"]
            if homes:
                return sorted(homes)
        r = len(live) if self._replication is None \
            else min(self._replication, len(live))
        order = sorted(live, key=lambda rep: (len(rep.models), rep.index))
        return sorted(rep.index for rep in order[:r])

    # ---------------------------------------------------------- warmup

    def warmup(self, *, prewarm: bool = True) -> int:
        """Prewarm every replica's bucket shapes and open the fleet for
        traffic (replicas move ``'warming'`` -> ``'serving'``; routing
        only ever considers serving replicas, so no replica takes
        traffic before its programs are warm).  ``prewarm=False``
        opens without compiling (the ``serve --no-warmup`` path).
        Returns the number of warm dispatches run."""
        n = 0
        for rep in self._replicas:
            if rep.state == "warming":
                n += self._warm_replica(rep, prewarm=prewarm)
        return n

    def _warm_replica(self, rep: _Replica, *, prewarm: bool = True) -> int:
        n = rep.engine.warmup() if prewarm and rep.models else 0
        rep.state = "serving"
        rep.last_beat = self._clock()
        rep.beat(force=True)                # fleet-status sees it live
        return n

    # ---------------------------------------------------------- routing

    def _hist(self, rep: _Replica, model_id, bucket: int):
        key = (rep.name, model_id, bucket)
        h = self._hists.get(key)
        if h is None:
            h = obs_metrics.REGISTRY.histogram(
                f"fleet.latency_ms.{rep.name}.{model_id}.b{bucket}")
            self._hists[key] = h
        return h

    def _estimates(self, rep: _Replica, model_id, bucket: int
                   ) -> Tuple[Optional[float], Optional[float]]:
        """(p50, p99) latency estimate for routing — ``(None, None)``
        while the histogram is cold.  Refreshed every
        ``ROUTE_REFRESH`` observations (docstring at the constant:
        per-request percentile() re-sorts dominated router overhead;
        mildly stale percentiles route identically)."""
        h = self._hist(rep, model_id, bucket)
        n = h.count
        if n < MIN_ROUTE_SAMPLES:
            return None, None
        key = (rep.name, model_id, bucket)
        cached = self._est.get(key)
        if cached is not None and n - cached[0] < ROUTE_REFRESH:
            return cached[1], cached[2]
        p50, p99 = h.percentile(0.50), h.percentile(0.99)
        self._est[key] = (n, p50, p99)
        return p50, p99

    def _candidates(self, model_id) -> List[_Replica]:
        idxs = self._placement.get(model_id)
        if idxs is None:
            raise KeyError(
                f"no resident model {model_id!r}; resident: "
                f"{self.models()}")
        cands = [self._replicas[i] for i in idxs
                 if self._replicas[i].state == "serving"]
        if not cands:
            states = {self._replicas[i].name: self._replicas[i].state
                      for i in idxs}
            raise ReplicaDeadError(
                f"no serving replica hosts model {model_id!r} "
                f"(placement: {states}; did you call warmup()?)")
        return cands

    def _route(self, model_id, m: int) -> _Replica:
        """Pick the replica for an m-row request — least expected
        latency on warm histograms, deterministic power-of-two-choices
        while cold — applying admission control first (module
        docstring).  Sheds raise :class:`FleetOverloadError`,
        recorded."""
        bucket = bucket_for(m, self.buckets)
        cands = self._candidates(model_id)
        ests = [(rep,) + self._estimates(rep, model_id, bucket)
                for rep in cands]
        if self.max_inflight is not None and all(
                rep.inflight >= self.max_inflight for rep in cands):
            self._record_shed(model_id)
            raise FleetOverloadError(
                f"all {len(cands)} replicas at max_inflight="
                f"{self.max_inflight} for model {model_id!r} — request "
                f"shed (explicit, counted in fleet.shed)")
        if self.slo_p99_ms is not None:
            known = [(rep, p99) for rep, _, p99 in ests
                     if p99 is not None]
            if known and len(known) == len(ests) and all(
                    (rep.inflight + 1) * p99 > self.slo_p99_ms
                    for rep, p99 in known):
                self._record_shed(model_id)
                raise FleetOverloadError(
                    f"expected completion exceeds the committed p99 "
                    f"bound {self.slo_p99_ms} ms on every replica for "
                    f"model {model_id!r} — request shed (explicit, "
                    f"counted in fleet.shed)")
        if all(p99 is not None for _, _, p99 in ests):
            # Least expected latency: typical service (p50) scaled by
            # the queue this request would join.
            best, best_exp = None, None
            for rep, p50, _ in ests:
                exp = (rep.inflight + 1) * (p50 or 0.0)
                if best_exp is None or exp < best_exp:
                    best, best_exp = rep, exp
            return best
        # Cold fallback: deterministic power-of-two-choices — two
        # candidates off a rotating counter, fewer in-flight wins.
        with self._lock:
            c = self._rr
            self._rr += 1
        a = cands[c % len(cands)]
        b = cands[(c + 1) % len(cands)]
        if b.inflight < a.inflight:
            return b
        return a

    def _record_route(self, replica_name: str, model_id,
                      n: int = 1) -> None:
        """Registry write-through for forwarded traffic — the
        ``fleet-record`` lint rule requires every forward site to call
        this (the SLO signal must never starve)."""
        with self._lock:
            self.routes += n
        reg = obs_metrics.REGISTRY
        reg.counter("fleet.route").inc(n)
        reg.counter(f"fleet.route.{replica_name}").inc(n)

    def _record_shed(self, model_id) -> None:
        """Registry write-through for shed traffic (explicit, counted,
        never silent — the admission-control contract)."""
        with self._lock:
            self.sheds += 1
        reg = obs_metrics.REGISTRY
        reg.counter("fleet.shed").inc()
        reg.counter(f"fleet.shed.{model_id}").inc()

    def _complete(self, rep: _Replica, model_id, rows, t0: float,
                  error: bool = False) -> None:
        """Release one in-flight slot; on success feed the routing
        histogram and the replica heartbeat."""
        dt_ms = (self._clock() - t0) * 1e3
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
        if error:
            return
        m = int(np.asarray(rows).shape[0]) if np.ndim(rows) > 1 else 1
        self._hist(rep, model_id, bucket_for(m, self.buckets)) \
            .observe(dt_ms)
        rep.last_beat = self._clock()
        rep.beat(rows=m)

    # ----------------------------------------------------- public calls

    def call(self, model_id, rows, *, op: str = "predict") -> np.ndarray:
        """Routed immediate dispatch (the single-request latency floor);
        fails over to a surviving replica if the target dies
        mid-request."""
        rows = np.asarray(rows)
        m = int(rows.shape[0]) if rows.ndim > 1 else 1
        while True:
            rep = self._route(model_id, m)
            try:
                return self._forward(rep, model_id, rows, op)
            except ReplicaDeadError:
                self._fail_over(rep)

    def predict(self, model_id, rows) -> np.ndarray:
        return self.call(model_id, rows)

    def score(self, model_id, rows) -> float:
        rows = np.asarray(rows)
        m = int(rows.shape[0]) if rows.ndim > 1 else 1
        while True:
            rep = self._route(model_id, m)
            self._record_route(rep.name, model_id)
            t0 = self._clock()
            with self._lock:
                rep.inflight += 1
            try:
                out = rep.engine.score(model_id, rows)
            except ReplicaDeadError:
                self._complete(rep, model_id, rows, t0, error=True)
                self._fail_over(rep)
                continue
            except Exception:
                self._complete(rep, model_id, rows, t0, error=True)
                raise
            self._complete(rep, model_id, rows, t0)
            return out

    def _forward(self, rep: _Replica, model_id, rows,
                 op: str) -> np.ndarray:
        """Forward one request synchronously to a replica engine,
        keeping the in-flight count and latency histogram honest."""
        self._record_route(rep.name, model_id)
        t0 = self._clock()
        with self._lock:
            rep.inflight += 1
        try:
            out = rep.engine.call(model_id, rows, op=op)
        except Exception:
            self._complete(rep, model_id, rows, t0, error=True)
            raise
        self._complete(rep, model_id, rows, t0)
        return out

    def submit(self, model_id, rows, *, op: str = "predict"
               ) -> FleetFuture:
        """Route one request into a replica's micro-batch queue;
        returns a :class:`FleetFuture` that transparently re-dispatches
        on replica death (sheds still raise here, immediately — an
        admission decision is made at submit time, not at collection)."""
        rows = np.asarray(rows)
        m = int(rows.shape[0]) if rows.ndim > 1 else 1
        rep = self._route(model_id, m)
        rep2, inner = self._submit_once(rep, model_id, rows, op)
        return FleetFuture(self, rep2, inner, model_id, rows, op,
                           self._clock())

    def _submit_once(self, rep: _Replica, model_id, rows, op: str
                     ) -> Tuple[_Replica, ServingFuture]:
        self._record_route(rep.name, model_id)
        with self._lock:
            rep.inflight += 1
        inner = rep.engine.submit(model_id, rows, op=op)
        return rep, inner

    def _resubmit(self, model_id, rows, op: str
                  ) -> Tuple[_Replica, ServingFuture]:
        """Re-dispatch a request whose replica died in flight (the
        FleetFuture fail-over path)."""
        m = int(np.asarray(rows).shape[0]) if np.ndim(rows) > 1 else 1
        rep = self._route(model_id, m)
        return self._submit_once(rep, model_id, rows, op)

    def predict_multi(self, requests: Sequence[Tuple[str, np.ndarray]]
                      ) -> List[np.ndarray]:
        """Routed mixed-model batch: forwarded WHOLE to one replica
        hosting every requested model (pack-group co-residency makes
        that the common case, so r11 packed dispatch stays alive
        fleet-wide); requests whose models share no replica fall back
        to per-request routing (correct, unpacked)."""
        if not requests:
            return []
        mids = {mid for mid, _ in requests}
        for mid in mids:
            if mid not in self._placement:
                raise KeyError(
                    f"no resident model {mid!r}; resident: "
                    f"{self.models()}")
        cands = [rep for rep in self._replicas
                 if rep.state == "serving" and mids <= rep.models]
        m = sum(int(np.asarray(rows).shape[0]) for _, rows in requests)
        while cands:
            # Same deterministic p2c on the co-resident candidates.
            with self._lock:
                c = self._rr
                self._rr += 1
            a = cands[c % len(cands)]
            b = cands[(c + 1) % len(cands)]
            rep = b if b.inflight < a.inflight else a
            self._record_route(rep.name, next(iter(mids)),
                               n=len(requests))
            t0 = self._clock()
            with self._lock:
                rep.inflight += 1
            try:
                out = rep.engine.predict_multi(requests)
            except ReplicaDeadError:
                self._complete(rep, next(iter(mids)), m, t0, error=True)
                self._fail_over(rep)
                cands = [r for r in cands if r is not rep]
                continue
            except Exception:
                self._complete(rep, next(iter(mids)), m, t0, error=True)
                raise
            self._complete(rep, next(iter(mids)), m, t0)
            return out
        # No single replica hosts them all: per-request routed calls.
        return [self.call(mid, rows) for mid, rows in requests]

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """Operator snapshot (the ``{"fleet_stats": true}`` payload):
        router counters, per-replica liveness/load/engine stats,
        placement and pack-group co-residency.  ``dispatches`` is the
        fleet total, so harnesses written against the engine surface
        (experiments/exp_serving_load.py) read it unchanged."""
        with self._lock:
            routes, sheds, redispatches = \
                self.routes, self.sheds, self.redispatches
        replicas = {}
        for rep in self._replicas:
            st = rep.engine.stats()
            replicas[rep.name] = {
                "state": rep.state, "inflight": int(rep.inflight),
                "models": sorted(rep.models),
                "dispatches": st["dispatches"],
                "packed_dispatches": st["packed_dispatches"],
                "queue": st["queue"],
                "prewarm_s": rep.prewarm_s,
            }
        models: Dict[str, dict] = {}
        for rep in self._replicas:
            for mid, m in rep.engine.stats()["models"].items():
                agg = models.setdefault(mid, {
                    "requests": 0, "rows": 0, "dispatches": 0,
                    "replicas": []})
                agg["requests"] += m["requests"]
                agg["rows"] += m["rows"]
                agg["dispatches"] += m["dispatches"]
                agg["replicas"].append(rep.name)
        return {
            "replicas": replicas,
            "n_replicas": len(self._replicas),
            "n_serving": sum(1 for r in self._replicas
                             if r.state == "serving"),
            "models": models,
            "placement": {mid: [self._replicas[i].name for i in idxs]
                          for mid, idxs in sorted(self._placement.items())},
            "pack_groups": {
                "/".join(map(str, key)): ids
                for key, ids in self.registry.pack_groups().items()},
            "routes": routes, "sheds": sheds,
            "redispatches": redispatches,
            "slo_p99_ms": self.slo_p99_ms,
            "max_inflight": self.max_inflight,
            "dispatches": sum(r["dispatches"] for r in replicas.values()),
            "buckets": list(self.buckets),
        }

    def quality_status(self) -> dict:
        """Per-model drift state per replica:
        ``{model_id: {replica: status-or-None}}`` (the fleet twin of
        ``ServingEngine.quality_status``; ``serve-status <fleet_dir>``
        renders the merged cross-replica view from the sinks)."""
        out: Dict[str, dict] = {}
        for rep in self._replicas:
            for mid, st in rep.engine.quality_status().items():
                out.setdefault(mid, {})[rep.name] = st
        return out

    def update_status(self) -> dict:
        """Per-model serve-and-learn state per replica:
        ``{model_id: {replica: status-or-None}}`` — the fleet twin of
        ``ServingEngine.update_status`` (ISSUE 20); the merged
        cross-replica update/rollback counts also land in
        ``serve-status <fleet_dir>`` via the quality sinks."""
        out: Dict[str, dict] = {}
        for rep in self._replicas:
            for mid, st in rep.engine.update_status().items():
                out.setdefault(mid, {})[rep.name] = st
        return out

    # -------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Drain and close every replica engine (idempotent)."""
        if self._closed:
            return
        self._closed = True
        for rep in self._replicas:
            rep.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
