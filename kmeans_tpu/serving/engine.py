"""Resident warm-kernel model server (ISSUE 6 tentpole).

The fit-time story (r6-r10) made training fast; the north star —
"heavy traffic from millions of users" — is assignment/scoring QPS,
and before this subsystem every ``predict`` call paid eager dispatch,
a fresh k x D parameter upload, and (on tunneled platforms) the
~70-100 ms RTT documented in docs/PERFORMANCE.md, with no way to
amortize across concurrent small requests.  The engine fixes all
three:

* **Resident models.**  ``add_model``/``load`` place a fitted model's
  tables on the mesh ONCE (``KMeans._cents_dev`` /
  ``GaussianMixture._params_dev`` instance caches — the same caches
  plain ``model.predict`` now uses, so engine and direct calls share
  one placement AND one compiled-function cache,
  ``models.kmeans._STEP_CACHE``).
* **Warm kernels, bucketed shapes.**  Requests pad to a small ladder
  of batch buckets (default 8/64/512/4096), so each (model family,
  bucket, dtype, mode) compiles once and every later dispatch reuses
  the executable.  On accelerators the per-dispatch staging buffer is
  DONATED (``make_predict_fn(donate_points=True)``) — it is single-use
  by construction.
* **Micro-batching.**  Concurrent small requests for the same model
  coalesce into one padded dispatch (``serving.batching``): bucketed
  sizes, a ``max_wait_ms`` flush timer, per-request result slices,
  rows never mixed across models inside a buffer.
* **Multi-model residency + routing.**  A registry
  (``serving.registry``) holds many fitted models; same-shape
  K-Means-family models pack on a batched model axis
  (``parallel.distributed.make_multi_predict_fn`` — the
  ``make_multi_fit_fn`` restart-batching idiom applied to inference),
  so a routed mixed-model batch is still ONE dispatch where shapes
  align.
* **Quantized fast path.**  ``quantize='bf16'`` serves assignment
  through the existing ``matmul_bf16`` distance mode (bf16 ``-2x·cᵀ``
  cross term, f32 norms + accumulation).  Labels are ordering-robust
  where distances round; ``verify_quantized`` pins a probe batch's
  labels bit-equal to the f32 path and reports the distance rtol —
  the acceptance gate tests/test_serving_parity.py enforces.

Parity contract: for every resident family the serving path produces
labels BIT-EQUAL to the model's own ``predict`` — the engine routes
through the same compiled assignment programs, modes, and resident
tables, so this is by construction, and tests/test_serving_parity.py
pins it across 1/2/4/8-way virtual meshes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from kmeans_tpu.models import kmeans as kmeans_mod
from kmeans_tpu.obs import drift as obs_drift
from kmeans_tpu.parallel import distributed as dist
from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
from kmeans_tpu.parallel.sharding import choose_chunk_size, shard_points
from kmeans_tpu.serving.batching import (DEFAULT_BUCKETS, MicroBatchQueue,
                                         ServingFuture, bucket_for,
                                         check_buckets)
from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.serving.registry import ModelRegistry
from kmeans_tpu.utils.profiling import note_dispatch

__all__ = ["ServingEngine", "ResidentModel"]

# bf16 fast-path mode map: which f32-class distance mode each serving
# mode quantizes to.  'direct' has no quantized form and stays exact;
# the guarded training rung is ALREADY the guarded bf16 path — no
# further quantization to apply.
_BF16_MODES = {"matmul": "matmul_bf16", "pallas": "pallas_bf16",
               "auto": "matmul_bf16"}

# Near-tie guard for the quantized assignment (ISSUE 6): a bf16 label
# is kept only when its argmin margin exceeds this fraction of the
# row's distance scale (|x|^2 + max|c|^2).  The bf16 cross-term error
# bound is ~2^-6 * scale on a distance DIFFERENCE
# (distributed.make_assign_margin_fn); 2^-5 is that bound doubled —
# flagged rows recompute at f32, which makes quantized labels
# bit-equal to the f32 oracle BY CONSTRUCTION, not just on separated
# data (the failure the end-to-end verify drive caught: 14/1000 flips
# on boundary rows of a 6-cluster blob set under plain bf16 argmin).
# Since ISSUE 8 the canonical bound lives with the shared guarded-
# assignment primitive (ops.assign.BF16_GUARD_RTOL) — serving and the
# training rung ('matmul_bf16_guarded') share ONE error model; this
# name re-exports it for the existing serving surface.
from kmeans_tpu.ops.assign import BF16_GUARD_RTOL as BF16_TIE_RTOL


#: Fitted-table attributes summed into a resident model's footprint
#: (whatever the family exposes; missing attrs contribute nothing).
_TABLE_ATTRS = ("centroids", "means_", "covariances_", "weights_",
                "precisions_cholesky_")


def _model_table_bytes(model) -> int:
    """Host-side bytes of a fitted model's parameter tables — the
    per-device residency cost of serving it (tables replicate across
    the data axis; a TP-sharded table costs 1/model_shards of this)."""
    total = 0
    for attr in _TABLE_ATTRS:
        arr = getattr(model, attr, None)
        nbytes = getattr(arr, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


class ResidentModel:
    """One resident model: the fitted estimator + its serving spec +
    per-model counters.  Device tables live on the MODEL's own caches
    (``_cents_dev`` / ``_params_dev``), so direct ``model.predict``
    calls and engine dispatches share one placement."""

    def __init__(self, model_id: str, model, spec: dict, quantize):
        self.model_id = model_id
        self.model = model
        self.spec = spec
        self.quantize = quantize
        # Per-model drift monitor (ISSUE 14); None when the engine runs
        # with quality monitoring off.  Fed exclusively with outputs
        # the dispatch already computed (engine._observe_quality).
        self.monitor: Optional[obs_drift.QualityMonitor] = None
        # Serve-and-learn actuator (ISSUE 20); None when the engine
        # runs without learn= or the model is not update-eligible.
        self.learner = None
        # bucket -> registry Histogram for request latency; resolved
        # once per (model, bucket) so the per-dispatch feed skips the
        # name build + registry lock (hot-path cost, BENCH_QUALITY).
        self._lat_hists: Dict[int, object] = {}
        self.requests = 0
        self.rows = 0
        self.dispatches = 0
        # Rows the bf16 near-tie guard re-labeled at f32 (audit trail
        # of the exactness guarantee; 0 on separated traffic).
        self.bf16_corrected_rows = 0
        # quantize='pq' residency (ISSUE 16): the table's product
        # quantizer + the compressed row codes, built once at add time;
        # pq_corrected_rows counts ADC near-ties re-resolved against
        # the decoded table (the r13 guard discipline applied to PQ).
        self.pq = None
        self.pq_codes: Optional[np.ndarray] = None
        self.pq_corrected_rows = 0
        # Resident table footprint (ISSUE 12): the bytes this model's
        # parameter tables hold on EACH device it is placed on (tables
        # are replicated across the data axis) — summed host-side from
        # the fitted arrays, so stats() answers "what does residency
        # cost" without touching the device.
        self.table_bytes = _model_table_bytes(model)

    def preprocess(self, rows: np.ndarray) -> np.ndarray:
        """Per-request input canonicalization: exactly what the model's
        own ``predict`` does to a raw array (SphericalKMeans
        normalizes rows in float64 before casting — the ``cache``
        path's arithmetic, bit for bit)."""
        dtype = np.dtype(self.spec["dtype"])
        if self.spec["normalize_inputs"]:
            from kmeans_tpu.models.spherical import _normalize_rows
            return _normalize_rows(
                np.asarray(rows, np.float64)).astype(dtype)
        return np.asarray(rows, dtype=dtype)


class ServingEngine:
    """Multi-model online serving over one mesh.

    Parameters
    ----------
    mesh : jax.sharding.Mesh or None
        The mesh every resident model serves from (None = all devices,
        data-parallel).  ``add_model`` re-points each model's ``mesh``
        here so direct calls and serving dispatches agree.
    buckets : ascending request-batch size ladder (compile once per
        bucket; oversize batches round up to a multiple of the top).
    max_wait_ms : float
        Micro-batch flush timer — the longest a queued request waits
        for co-batchable traffic (latency floor of the ``submit``
        path; ``predict`` dispatches immediately).
    clock, start : forwarded to :class:`MicroBatchQueue` (injectable
        clock / no-worker mode for deterministic tests).
    donate : 'auto' | bool
        Donate the per-dispatch staging buffer to the assignment
        program.  'auto' = on accelerators only (CPU ignores donation
        and would warn).
    quality : 'auto' | bool
        Per-model drift monitoring (ISSUE 14): every dispatch path
        feeds its ALREADY-COMPUTED labels/distances into a
        :class:`~kmeans_tpu.obs.drift.QualityMonitor` — zero extra
        dispatches, labels bit-exact with monitoring off (the obs=0
        parity contract, pinned by tests/test_quality.py) — and
        per-(model, bucket) latency histograms land in the metrics
        registry.  'auto' (default) resolves ON on accelerators —
        where a dispatch pays the ~70-100 ms tunneled RTT and the
        host-side feed is < 0.2% — and OFF on CPU, where the
        BENCH_QUALITY row MEASURED the per-dispatch feed breaching
        the committed <= 1.01 overhead rule against sub-ms local
        dispatches (the r8/r13 'auto'-resolution discipline: the
        measured rejection is published, the knob stays).
    quality_dir : directory for per-model drift JSONL sinks
        (``quality.<model_id>.jsonl`` — the ``serve-status`` input);
        None (default) keeps monitoring in-memory only.
    quality_window : rows per drift-evaluation window
        (:data:`~kmeans_tpu.obs.drift.DRIFT_WINDOW_ROWS` default).
    quality_tag : suffix for the per-model quality sink filenames
        (``quality.<model_id>.<tag>.jsonl``) so N fleet replicas
        (ISSUE 17) sharing one ``quality_dir`` keep distinct sinks —
        the ``serve-status`` multi-file reader merges them per model.
        None (default) keeps the documented single-engine name.
    learn : False | True | dict
        Serve-and-learn actuator (ISSUE 20).  ``True`` attaches a
        :class:`~kmeans_tpu.serving.learn.ModelLearner` to every
        eligible resident (MiniBatch-style ``partial_fit`` family,
        monitored, not PQ-compressed): the model updates IN PLACE from
        sampled live traffic when its drift monitor fires — snapshot
        first, one atomic table swap, rollback on regression.  A dict
        enables learning AND overrides the committed constants per
        engine (keys: ``dir`` for the snapshot directory — defaults to
        ``quality_dir`` — plus any :class:`ModelLearner` budget/
        threshold kwarg).  Requires quality monitoring to resolve ON:
        the learn trigger IS the drift monitor.
    """

    def __init__(self, *, mesh=None, buckets=DEFAULT_BUCKETS,
                 max_wait_ms: float = 2.0, clock=None, start: bool = True,
                 donate="auto", quality="auto", quality_dir=None,
                 quality_window: Optional[int] = None,
                 quality_tag: Optional[str] = None,
                 learn=False):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.buckets = check_buckets(buckets)
        self.registry = ModelRegistry()
        self._residents: Dict[str, ResidentModel] = {}
        if donate == "auto":
            donate = jax.default_backend() not in ("cpu",)
        self._donate = bool(donate)
        # (tuple of model ids) -> (per-model centroid identity tokens,
        # device-placed (M, k, D) stack) for packed mixed-model routing.
        self._pack_cache: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        # warmup() probes run through the real dispatch path; this
        # thread-local flag makes _record (and the bf16 audit counter)
        # skip them so stats reflect served traffic only — a rollback
        # snapshot would race concurrent requests and miss the audit
        # counter (review finding).
        self._tls = threading.local()
        # Bucket-fill histogram: bucket -> [dispatches, real rows].
        self._fill: Dict[int, List[int]] = {}
        if quality not in ("auto", True, False):
            raise ValueError(f"quality must be 'auto', True or False, "
                             f"got {quality!r}")
        if quality == "auto":
            # Writing quality sinks is asking for monitoring: a
            # --quality-dir serve on CPU must not silently produce
            # empty files because 'auto' resolved off.
            quality = quality_dir is not None \
                or jax.default_backend() not in ("cpu",)
        self._quality = bool(quality)
        self._quality_dir = str(quality_dir) if quality_dir is not None \
            else None
        self._quality_window = int(quality_window) \
            if quality_window is not None else obs_drift.DRIFT_WINDOW_ROWS
        self._quality_tag = str(quality_tag) if quality_tag is not None \
            else None
        # Serve-and-learn actuator config (ISSUE 20): False -> off,
        # True -> committed defaults, dict -> per-engine overrides.
        if learn in (False, None):
            self._learn_cfg = None
        else:
            cfg = {} if learn is True else dict(learn)
            if not isinstance(cfg, dict):
                raise ValueError(f"learn must be False, True or a dict "
                                 f"of overrides, got {learn!r}")
            allowed = {"dir", "batch_rows", "max_batches",
                       "reservoir_rows", "min_rows", "update_budget",
                       "rollback_budget", "cooldown_windows",
                       "regression_ratio", "eval_windows"}
            unknown = set(cfg) - allowed
            if unknown:
                raise ValueError(f"unknown learn config keys "
                                 f"{sorted(unknown)}; allowed: "
                                 f"{sorted(allowed)}")
            if not self._quality:
                raise ValueError(
                    "learn requires quality monitoring: the "
                    "serve-and-learn trigger IS the drift monitor "
                    "(pass quality=True, or a quality_dir)")
            self._learn_cfg = cfg
        self._learn_dir = None          # lazily resolved snapshot dir
        # Fleet glue (ISSUE 17): an optional pre-dispatch hook, called
        # with (model_id, op) before EVERY dispatch — direct, queued,
        # and packed.  The fleet's replica wrapper raises
        # ReplicaDeadError here when the replica is killed, so queued
        # batches fail through the queue's existing per-member
        # isolation and the router can re-dispatch each request.
        self.dispatch_guard = None
        self.dispatches = 0
        self.packed_dispatches = 0
        self.queue = MicroBatchQueue(
            self._dispatch, buckets=self.buckets,
            max_wait_ms=max_wait_ms, clock=clock, start=start,
            validate=self._validate)

    # -------------------------------------------------------- residency

    def add_model(self, model_id: str, model, *,
                  quantize: Optional[str] = None,
                  profile: Optional[dict] = None) -> ResidentModel:
        """Make a FITTED model resident.  ``quantize='bf16'`` serves
        its assignment through the bf16 cross-term fast path (labels
        pinned against the f32 path by ``verify_quantized``).

        ``profile`` overrides the drift-monitor reference window
        (ISSUE 14); by default the model's own ``quality_profile()`` —
        fresh fitted stats or the checkpoint-restored block — is used.
        A model with neither serves with the reference-free detector
        subset (bf16 margin shift + latency histograms only).

        ``quantize='pq'`` (ISSUE 16) compresses the centroid table with
        a product quantizer trained at add time and serves ``predict``
        through the ADC route (``ProductQuantizer.adc_assign``): labels
        are the exact argmin over the DECODED table — near-ties under
        the r13 margin guard re-resolve exactly — with the quantization
        residual of the stored codes as the one documented
        approximation.  ``transform``/``score_rows`` keep the exact
        table."""
        if quantize not in (None, "bf16", "pq"):
            raise ValueError(f"quantize must be None, 'bf16' or 'pq', "
                             f"got {quantize!r}")
        if quantize is not None and mesh_shape(self.mesh)[1] != 1:
            raise ValueError(
                f"quantize={quantize!r} requires a data-parallel mesh "
                "(neither the guarded bf16 assignment nor the PQ-ADC "
                "route has a TP centroid-sharding form); serve this "
                "model unquantized or use model_shards=1")
        spec = self.registry.register(model_id, model)
        if spec.get("assign") == "two_level":
            if mesh_shape(self.mesh)[1] != 1:
                self.registry.remove(model_id)
                raise ValueError(
                    "a two-level (assign='two_level') model requires a "
                    "data-parallel serving mesh (model_shards == 1): "
                    "the coarse->candidates route addresses the same "
                    "memory wall as TP centroid sharding and the two "
                    "tiers do not stack")
            if quantize is not None:
                self.registry.remove(model_id)
                raise ValueError(
                    "quantize does not compose with assign='two_level' "
                    "— the quantized fast paths score the DENSE table, "
                    "the two-level route a candidate subset; serve one "
                    "approximation at a time")
        # One mesh for everything resident: direct model calls and
        # serving dispatches must hit the same compiled programs.
        model.mesh = self.mesh
        if spec["family"] == "gmm":
            quantize = None       # quantized assign is K-Means-family
        rm = ResidentModel(model_id, model, spec, quantize)
        if quantize == "pq":
            from kmeans_tpu.models.pq import ProductQuantizer
            rm.pq, rm.pq_codes = ProductQuantizer.for_table(
                np.asarray(model.centroids), mesh=self.mesh,
                seed=int(getattr(model, "seed", 0)))
        if self._quality:
            if profile is None:
                qp = getattr(model, "quality_profile", None)
                profile = qp() if callable(qp) else None
            sink_name = f"quality.{model_id}.jsonl" \
                if self._quality_tag is None \
                else f"quality.{model_id}.{self._quality_tag}.jsonl"
            sink = os.path.join(self._quality_dir, sink_name) \
                if self._quality_dir is not None else None
            rm.monitor = obs_drift.QualityMonitor(
                model_id, spec["k"], profile=profile,
                window_rows=self._quality_window, sink_path=sink)
        self._attach_learner(rm)
        self._residents[model_id] = rm
        return rm

    def _attach_learner(self, rm: ResidentModel) -> None:
        """Attach the serve-and-learn actuator (ISSUE 20) when the
        engine runs with ``learn=`` and the model is update-eligible:
        K-Means family with a real ``partial_fit`` (the MiniBatch
        Sculley carry IS the update engine), monitored (the trigger is
        the drift monitor), and not ``quantize='pq'`` — the PQ codes
        are trained against the ADD-TIME table, so an in-place swap
        would serve stale codes against a moved table.  ``bf16`` is
        fine (it reads the live ``_cents_dev`` placement), and a
        two-level resident never gets here (its coarse route has no
        ``partial_fit``).  Ineligible models serve unchanged with
        ``update_status()[model_id] is None``."""
        if self._learn_cfg is None or rm.monitor is None:
            return
        if not rm.spec.get("updatable") or rm.quantize == "pq":
            return
        if rm.spec.get("assign") == "two_level":
            return
        from kmeans_tpu.serving import learn as serve_learn
        if self._learn_dir is None:
            self._learn_dir = self._learn_cfg.get("dir") \
                or self._quality_dir
            if self._learn_dir is None:
                import tempfile
                self._learn_dir = tempfile.mkdtemp(prefix="kmeans-learn-")
        kwargs = {k: v for k, v in self._learn_cfg.items() if k != "dir"}
        rm.learner = serve_learn.ModelLearner(
            self, rm,
            snapshot_path=serve_learn.snapshot_path_for(
                self._learn_dir, rm.model_id, self._quality_tag),
            **kwargs)

    def load(self, path, model_id: Optional[str] = None, *,
             quantize: Optional[str] = None) -> str:
        """Load a topology-portable checkpoint (any family, any mesh it
        was written on — r10) and make it resident.  The checkpoint's
        quality-profile metadata block (ISSUE 14) becomes the drift
        monitor's reference window."""
        mid, model = self.registry.load(path, model_id)
        # registry.load registered it; wrap without re-registering.
        self.registry.remove(mid)
        self.add_model(mid, model, quantize=quantize)
        return mid

    def remove(self, model_id: str) -> None:
        self.registry.remove(model_id)
        rm = self._residents.pop(model_id)
        # Learner FIRST, and joined: an in-flight update must finish
        # (or abort unpublished) BEFORE the monitor sink closes —
        # otherwise the update's decision record is a write-after-
        # remove to a freed sink (ISSUE 20 satellite; the
        # QualityMonitor.close() class of bug).
        if rm.learner is not None:
            rm.learner.close(join=True)
        if rm.monitor is not None:
            rm.monitor.close()
        with self._lock:
            self._pack_cache = {ids: v for ids, v in
                                self._pack_cache.items()
                                if model_id not in ids}

    def models(self) -> List[str]:
        return self.registry.ids()

    def _rm(self, model_id: str) -> ResidentModel:
        try:
            return self._residents[model_id]
        except KeyError:
            raise KeyError(
                f"no resident model {model_id!r}; resident: "
                f"{sorted(self._residents)}") from None

    # ------------------------------------------------------- validation

    def _validate(self, model_id, op: str, rows) -> np.ndarray:
        """Canonicalize one request's rows; every failure here is
        per-request (submit-time poison isolation)."""
        rm = self._rm(model_id)
        if op not in rm.spec["ops"]:
            raise ValueError(
                f"op {op!r} not served for model {model_id!r} "
                f"(family {rm.spec['family']}); available: "
                f"{rm.spec['ops']}")
        rows = np.asarray(rows)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.ndim != 2 or rows.shape[1] != rm.spec["d"]:
            raise ValueError(
                f"request rows must be (m, {rm.spec['d']}) for model "
                f"{model_id!r}, got shape {rows.shape}")
        if rows.shape[0] == 0:
            raise ValueError("request must contain at least one row")
        block = rm.preprocess(rows)
        if not np.all(np.isfinite(block)):
            raise ValueError(
                f"request for model {model_id!r} contains non-finite "
                f"values")
        return block

    # --------------------------------------------------------- dispatch

    def _record(self, rm: ResidentModel, bucket: int, m: int,
                n_requests: int = 1) -> None:
        if getattr(self._tls, "warming", False):
            return
        with self._lock:
            self.dispatches += 1
            rm.dispatches += 1
            rm.requests += n_requests
            rm.rows += m
            fill = self._fill.setdefault(bucket, [0, 0])
            fill[0] += 1
            fill[1] += m
        # Write-through (ISSUE 11): the engine counters stay the
        # per-engine surface; the registry keeps the process view.
        reg = obs_metrics.REGISTRY
        reg.counter("serve.dispatches").inc()
        reg.counter("serve.requests").inc(n_requests)
        reg.counter("serve.rows").inc(m)

    def _observe_quality(self, rm: ResidentModel, bucket: int,
                         dt_s: Optional[float], *, rows: int = 0,
                         labels=None, score=None, near_ties: int = 0,
                         guarded_rows: int = 0) -> None:
        """Feed one dispatch's ALREADY-COMPUTED outputs into the
        model's drift monitor + the per-(model, bucket) latency
        histogram (ISSUE 14).  Host-side reads only — never an extra
        dispatch, never a write into the result arrays, skipped for
        warmup probes — so monitoring on/off is label-bit-exact and
        dispatch-count-identical by construction."""
        if rm.monitor is None or getattr(self._tls, "warming", False):
            return
        if dt_s is not None:
            hist = rm._lat_hists.get(bucket)
            if hist is None:
                hist = obs_metrics.REGISTRY.histogram(
                    f"serve.latency_ms.{rm.model_id}.b{bucket}")
                rm._lat_hists[bucket] = hist
            hist.observe(dt_s * 1e3)
        rm.monitor.observe(rows, labels=labels, score=score,
                           near_ties=near_ties,
                           guarded_rows=guarded_rows)

    def _feed_learner(self, rm: ResidentModel, rows: np.ndarray) -> None:
        """Serve-and-learn reservoir tap (ISSUE 20): retain THIS
        dispatch's already-materialized rows and run the O(1) trigger
        check.  Same discipline as the quality feed it rides next to —
        host-side only, never an extra dispatch, warmup probes
        excluded — so learning off/idle is dispatch-count-identical to
        learning absent."""
        ln = rm.learner
        if ln is None or getattr(self._tls, "warming", False):
            return
        ln.offer(rows)
        ln.poke()

    def _kmeans_modes(self, rm: ResidentModel, B: int) -> Tuple[str, str]:
        """(assign mode, transform mode) for a bucket-B dispatch —
        the model's own 'auto' resolution, then the bf16 fast-path
        substitution when this resident is quantized."""
        mode = rm.model._mode(B, rm.spec["d"])
        if rm.quantize == "bf16":
            mode = _BF16_MODES.get(mode, mode)
        from kmeans_tpu.ops.assign import value_mode
        tmode = value_mode({"auto": "matmul", "pallas": "matmul",
                            "pallas_bf16": "matmul_bf16"}.get(mode, mode))
        return mode, tmode

    def _predict_fn(self, chunk: int, mode: str):
        """The assignment program for one bucket shape.  CPU: exactly
        ``KMeans.predict``'s cached function (ONE shared cache —
        ISSUE 6 satellite).  Accelerators: a donating twin under its
        own key (the shared fn must never donate a retained
        ShardedDataset's points)."""
        if not self._donate:
            return kmeans_mod._get_step_fns(self.mesh, chunk, mode)[1]
        return kmeans_mod._STEP_CACHE.get_or_create(
            (self.mesh, chunk, mode, "serve-donate"),
            lambda: dist.make_predict_fn(
                self.mesh, chunk_size=chunk, mode=mode,
                donate_points=True))

    def _serve_chunk(self, rm: ResidentModel, B: int) -> int:
        """Scan chunk for a bucket-B dispatch: always the AUTO
        (VMEM-budget) rule at the bucket shape — NEVER the model's
        explicit training ``chunk_size`` (review finding: a model
        fitted with chunk_size=2M would pad an 8-row request to
        data_shards x 2M zero rows per dispatch).  Per-row labels are
        chunk-invariant, so this cannot change results vs the
        model's own ``predict``."""
        data_shards, model_shards = mesh_shape(self.mesh)
        return choose_chunk_size(
            -(-B // data_shards),
            max(rm.model._tile_k(B, rm.spec["d"]), model_shards),
            rm.spec["d"])

    def _stage(self, rm: ResidentModel, rows: np.ndarray
               ) -> Tuple[np.ndarray, int, int]:
        """Pad validated rows into this request batch's bucket buffer."""
        m = rows.shape[0]
        B = bucket_for(m, self.buckets)
        d = rm.spec["d"]
        buf = np.zeros((B, d), dtype=np.dtype(rm.spec["dtype"]))
        buf[:m] = rows
        return buf, m, B

    def _dispatch(self, model_id, op: str, rows: np.ndarray) -> np.ndarray:
        """One coalesced batch -> per-row result array (axis 0 aligned
        with ``rows``; the queue slices per request)."""
        guard = self.dispatch_guard
        if guard is not None:
            guard(model_id, op)
        rm = self._rm(model_id)
        if rm.spec["family"] == "gmm":
            return self._dispatch_gmm(rm, op, rows)
        return self._dispatch_kmeans(rm, op, rows)

    def _dispatch_kmeans(self, rm: ResidentModel, op: str,
                         rows: np.ndarray) -> np.ndarray:
        model = rm.model
        buf, m, B = self._stage(rm, rows)
        mode, tmode = self._kmeans_modes(rm, B)
        chunk = self._serve_chunk(rm, B)
        data_shards, model_shards = mesh_shape(self.mesh)
        corrected = 0
        guarded = 0
        t0 = time.perf_counter()
        # 'serve.request' span (ISSUE 11): one coalesced serving
        # dispatch — covers staging + the compiled call + the result
        # transfer (np.asarray is the sync point).
        with obs_trace.span("serve.request", model=rm.model_id, op=op,
                            rows=m, bucket=B):
            cents_dev = model._cents_dev(self.mesh, model_shards)
            pts, _ = shard_points(buf, self.mesh, chunk)
            if op == "predict":
                if rm.quantize == "bf16":
                    out, corrected = self._assign_bf16_guarded(
                        rm, buf, pts, cents_dev, chunk, m)
                    guarded = m
                    if corrected and not getattr(self._tls, "warming",
                                                 False):
                        with self._lock:
                            rm.bf16_corrected_rows += corrected
                elif rm.quantize == "pq":
                    out, corrected = self._assign_pq(rm, buf, m)
                    guarded = m
                    if corrected and not getattr(self._tls, "warming",
                                                 False):
                        with self._lock:
                            rm.pq_corrected_rows += corrected
                elif rm.spec.get("assign") == "two_level":
                    out = self._assign_two_level(
                        rm, pts, cents_dev, chunk, tmode, m)
                else:
                    out = np.asarray(self._predict_fn(chunk, mode)(
                        pts, cents_dev, np.int32(m)))[:m]
            elif op == "transform":
                tfn = kmeans_mod._STEP_CACHE.get_or_create(
                    (self.mesh, chunk, tmode, "transform"),
                    lambda: dist.make_transform_fn(
                        self.mesh, chunk_size=chunk, mode=tmode))
                out = np.asarray(tfn(pts, cents_dev))[:m, : rm.spec["k"]]
            elif op == "score_rows":
                # Key on the VALUE-surface mode: make_score_rows_fn maps
                # the guarded rung to 'matmul' internally, so the raw
                # mode would duplicate an identical compile next to the
                # f32 entry.
                from kmeans_tpu.ops.assign import value_mode
                smode = value_mode(mode)
                sfn = kmeans_mod._STEP_CACHE.get_or_create(
                    (self.mesh, chunk, smode, "score_rows"),
                    lambda: dist.make_score_rows_fn(
                        self.mesh, chunk_size=chunk, mode=smode))
                out = np.asarray(sfn(pts, cents_dev))[:m]
            else:                           # unreachable past _validate
                raise ValueError(f"unknown op {op!r}")
        self._record(rm, B, m)
        # Quality feed (ISSUE 14): exactly what THIS dispatch already
        # computed — labels for predict (plus the bf16 guard's
        # correction count), per-row nearest squared distance for
        # score_rows; transform feeds rows only (deriving a min over k
        # columns would be new host work the overhead rule forbids).
        self._observe_quality(
            rm, B, time.perf_counter() - t0, rows=m,
            labels=out if op == "predict" else None,
            score=out if op == "score_rows" else None,
            near_ties=corrected, guarded_rows=guarded)
        self._feed_learner(rm, rows)
        return out

    def _assign_bf16_guarded(self, rm: ResidentModel, buf: np.ndarray,
                             pts, cents_dev, chunk: int, m: int
                             ) -> Tuple[np.ndarray, int]:
        """The quantized fast path with exact argmin tie-break
        verification: bf16 distances decide every row whose argmin
        margin clears ``BF16_TIE_RTOL`` of the row's distance scale;
        the (rare) flagged near-tie rows are re-labeled by one small
        f32 dispatch.  Result: labels bit-equal to the f32 oracle BY
        CONSTRUCTION — the bf16 error bound can only reorder distances
        inside the guarded margin.  Returns (labels, corrected_count);
        the CALLER owns the audit counter (verify_quantized probes
        through here without touching the resident's state)."""
        with obs_trace.span("dispatch", tag="serve/bf16-margin", rows=m):
            fn = kmeans_mod._STEP_CACHE.get_or_create(
                (self.mesh, chunk, "assign-margin"),
                lambda: dist.make_assign_margin_fn(
                    self.mesh, chunk_size=chunk, mode="matmul_bf16"))
            labels, margin, scale = fn(pts, cents_dev)
            labels = np.array(np.asarray(labels)[:m])
            margin = np.asarray(margin)[:m]
            scale = np.asarray(scale)[:m]
        near = np.flatnonzero(margin <= BF16_TIE_RTOL * scale)
        if near.size:
            # f32 correction ride-along: its own (small) bucket, the
            # SHARED f32 predict program.  Tagged distinctly so
            # dispatch-count pins can tell guard traffic from serving
            # traffic (ISSUE 8 satellite).
            note_dispatch("bf16-guard-fix")
            with obs_trace.span("dispatch", tag="serve/bf16-guard-fix",
                                rows=int(near.size)):
                sub = np.ascontiguousarray(buf[near])
                sub_buf, n_sub, B_sub = self._stage(rm, sub)
                sub_chunk = self._serve_chunk(rm, B_sub)
                sub_pts, _ = shard_points(sub_buf, self.mesh, sub_chunk)
                # The model's OWN f32-class mode (not the bf16 map) —
                # the corrected rows must match whatever
                # ``model.predict`` runs.
                f32_mode = rm.model._mode(B_sub, rm.spec["d"])
                exact = np.asarray(self._predict_fn(sub_chunk, f32_mode)(
                    sub_pts, rm.model._cents_dev(
                        self.mesh, mesh_shape(self.mesh)[1]),
                    np.int32(n_sub)))[:n_sub]
                labels[near] = exact
        return labels, int(near.size)

    def _assign_pq(self, rm: ResidentModel, buf: np.ndarray, m: int
                   ) -> Tuple[np.ndarray, int]:
        """The ``quantize='pq'`` predict route (ISSUE 16): ADC lookup
        sums against the compressed table, with the r13 margin guard
        re-resolving near-ties exactly against the DECODED table
        (``ProductQuantizer.adc_assign`` — labels equal the exact
        decoded-table argmin by construction).  Host-side: the whole
        route is O(m * (k_table + k_pq * d)) numpy on tiny tables —
        no compiled program, hence no note_dispatch."""
        with obs_trace.span("dispatch", tag="serve/pq-adc", rows=m):
            labels, corrected = rm.pq.adc_assign(buf[:m], rm.pq_codes)
        return labels, int(corrected)

    def _assign_two_level(self, rm: ResidentModel, pts, cents_dev,
                          chunk: int, tmode: str, m: int) -> np.ndarray:
        """The two-level predict route for a resident
        ``assign='two_level'`` model (ISSUE 16): the model's own
        coarse/member tables (cached by centroid identity) through the
        coarse->candidates->exact-recompute program, at the SERVING
        bucket's chunk shape.  Same cache key family as
        ``KMeans._predict_two_level_labels``, so a model served and
        called directly shares compiled programs whenever the shapes
        agree."""
        model = rm.model
        coarse, members = model._two_level_tables()
        C, npb = model._two_level_params()
        fn = kmeans_mod._STEP_CACHE.get_or_create(
            (self.mesh, chunk, tmode, C, members.shape[1], npb,
             "twolevel-predict"),
            lambda: dist.make_two_level_predict_fn(
                self.mesh, chunk_size=chunk, nprobe=npb, mode=tmode))
        # Tagged distinctly from dense serving traffic so dispatch-
        # count pins can tell the routes apart (the bf16-guard-fix
        # discipline).
        note_dispatch("serve/two-level")
        with obs_trace.span("dispatch", tag="serve/two-level", rows=m):
            return np.asarray(fn(pts, cents_dev,
                                 coarse.astype(model.dtype),
                                 members))[:m]

    def _dispatch_gmm(self, rm: ResidentModel, op: str,
                      rows: np.ndarray) -> np.ndarray:
        """Mixture ops ride the model's own ``_posterior`` — parity
        with ``GaussianMixture.predict`` is by construction, and the
        ISSUE-6 ``_params_dev`` cache makes it warm (tables placed
        once, compiled pass reused per bucket shape)."""
        buf, m, B = self._stage(rm, rows)
        t0 = time.perf_counter()
        with obs_trace.span("serve.request", model=rm.model_id, op=op,
                            rows=m, bucket=B):
            labels, logr, lse = rm.model._posterior(buf)
        self._record(rm, B, m)
        # Quality feed (ISSUE 14): the posterior pass computes labels
        # AND per-row log-likelihood for EVERY mixture op, so both
        # detectors feed on every dispatch — score in the profile's
        # neg_log_lik convention (-log p(x) per row).
        self._observe_quality(rm, B, time.perf_counter() - t0, rows=m,
                              labels=labels[:m], score=-lse[:m])
        if op == "predict":
            return labels[:m]
        if op == "predict_proba":
            return np.exp(logr)[:m]
        return lse[:m]                      # 'score_samples'

    # ----------------------------------------------------- public calls

    def call(self, model_id, rows, *, op: str = "predict") -> np.ndarray:
        """Immediate (un-queued) warm dispatch of one request — the
        latency floor.  This is the right path for a strictly serial
        caller (e.g. the ``serve`` CLI's stdin loop): going through
        ``submit`` instead would pay the ``max_wait_ms`` flush timer on
        every request for coalescing that can never happen (review
        finding).  Use ``submit`` when concurrent callers can share a
        dispatch."""
        return self._dispatch(model_id, op,
                              self._validate(model_id, op, rows))

    def predict(self, model_id, rows) -> np.ndarray:
        """Immediate (un-queued) warm dispatch — the latency floor."""
        return self.call(model_id, rows)

    def submit(self, model_id, rows, *, op: str = "predict"
               ) -> ServingFuture:
        """Queue one request for micro-batching; returns a future whose
        ``result()`` is this request's own rows' slice."""
        return self.queue.submit(model_id, rows, op=op)

    def score(self, model_id, rows) -> float:
        """Model-family score of one request batch: K-Means negative
        SSE (sum of per-row nearest squared distances, f64 host sum);
        GMM mean per-sample log-likelihood (sklearn conventions)."""
        rm = self._rm(model_id)
        if rm.spec["family"] == "gmm":
            lse = self._dispatch(model_id, "score_samples",
                                 self._validate(model_id,
                                                "score_samples", rows))
            return float(np.mean(lse))
        mind2 = self._dispatch(model_id, "score_rows",
                               self._validate(model_id, "score_rows",
                                              rows))
        return -float(np.sum(np.asarray(mind2, np.float64)))

    def predict_multi(self, requests: Sequence[Tuple[str, np.ndarray]]
                      ) -> List[np.ndarray]:
        """Routed mixed-model batch: one (model_id, rows) pair per
        request, results in request order.

        Requests whose models share a pack group (same-(k, D, dtype)
        K-Means family, data-parallel mesh) are served by ONE packed
        dispatch — every packed row labeled under every packed model
        (``make_multi_predict_fn``), each request keeping its own
        model's labels.  Everything else dispatches per model.  Labels
        are pinned equal to per-model sequential ``predict`` results
        (tests/test_serving_parity.py)."""
        blocks = [self._validate(mid, "predict", rows)
                  for mid, rows in requests]
        _, model_shards = mesh_shape(self.mesh)
        groups: Dict[tuple, List[int]] = {}
        singles: List[int] = []
        for i, (mid, _) in enumerate(requests):
            key = self.registry.group_key(self._rm(mid).spec)
            if key is None or model_shards != 1:
                singles.append(i)
            else:
                groups.setdefault(key, []).append(i)
        out: List[Optional[np.ndarray]] = [None] * len(requests)
        for key, idxs in groups.items():
            ids = []
            for i in idxs:
                if requests[i][0] not in ids:
                    ids.append(requests[i][0])
            if len(ids) < 2:
                singles.extend(idxs)
                continue
            packed = self._dispatch_packed(
                ids, [(requests[i][0], blocks[i]) for i in idxs])
            for i, lab in zip(idxs, packed):
                out[i] = lab
        for i in singles:
            out[i] = self._dispatch(requests[i][0], "predict", blocks[i])
        return out

    def _pack_stack(self, ids: Tuple[str, ...]):
        """Device-placed (M, k, D) centroid stack for a pack, cached and
        invalidated on any member's ``centroids`` identity change."""
        rms = [self._rm(mid) for mid in ids]
        tokens = tuple(rm.model.centroids for rm in rms)
        with self._lock:
            cached = self._pack_cache.get(ids)
            if cached is not None and all(
                    a is b for a, b in zip(cached[0], tokens)):
                return cached[1]
        dtype = np.dtype(rms[0].spec["dtype"])
        stack = np.stack([np.asarray(rm.model.centroids, dtype=dtype)
                          for rm in rms])
        dev = jax.device_put(stack)
        with self._lock:
            self._pack_cache[ids] = (tokens, dev)
        return dev

    def _dispatch_packed(self, ids: List[str],
                         items: List[Tuple[str, np.ndarray]]
                         ) -> List[np.ndarray]:
        """One batched-model dispatch over every item's rows; returns
        per-item label arrays (item order preserved)."""
        guard = self.dispatch_guard
        if guard is not None:
            guard(tuple(ids), "predict_multi")
        ids = tuple(ids)
        slot = {mid: j for j, mid in enumerate(ids)}
        rms = {mid: self._rm(mid) for mid in ids}
        rows = np.concatenate([b for _, b in items], axis=0)
        first = rms[ids[0]]
        d = first.spec["d"]
        buf, m, B = self._stage(first, rows)
        # Packed routing serves at the f32-class mode even when members
        # are quantized: make_multi_predict_fn has no near-tie guard,
        # and plain bf16 argmin is NOT label-exact (review finding —
        # 19/28 flips on boundary rows), so exactness wins over the
        # bf16 rate until a guarded packed form is built and measured.
        mode = first.model._mode(B, d)
        chunk = self._serve_chunk(first, B)
        t0 = time.perf_counter()
        with obs_trace.span("serve.request", op="predict_multi",
                            models=len(ids), rows=m, bucket=B):
            fn = kmeans_mod._STEP_CACHE.get_or_create(
                (self.mesh, chunk, mode, len(ids), "multipredict"),
                lambda: dist.make_multi_predict_fn(
                    self.mesh, chunk_size=chunk, mode=mode,
                    n_models=len(ids)))
            pts, _ = shard_points(buf, self.mesh, chunk)
            stack = self._pack_stack(ids)
            labels_all = np.asarray(fn(pts, stack))  # (M, B_padded)
        # ONE physical dispatch: the global count and the bucket-fill
        # histogram record it once (with the batch's total real rows);
        # per-model counters record each member's share (a member's
        # `dispatches` counts dispatches that INCLUDED it, so per-model
        # sums may exceed the global count for packed traffic).
        with self._lock:
            self.packed_dispatches += 1
            self.dispatches += 1
            fill = self._fill.setdefault(B, [0, 0])
            fill[0] += 1
            fill[1] += m
            for mid in ids:
                rms[mid].dispatches += 1
            for mid, block in items:
                rms[mid].requests += 1
                rms[mid].rows += block.shape[0]
        dt = time.perf_counter() - t0
        results = []
        off = 0
        for mid, block in items:
            mb = block.shape[0]
            results.append(labels_all[slot[mid], off: off + mb].copy())
            off += mb
        # Quality feed (ISSUE 14): each packed member's monitor sees
        # ITS OWN requests' labels under its own model's slot — the
        # packed dispatch labeled every row under every model, but
        # foreign rows are foreign traffic, not this model's serving
        # distribution.
        for (mid, block), lab in zip(items, results):
            self._observe_quality(rms[mid], B, dt, rows=block.shape[0],
                                  labels=lab)
            self._feed_learner(rms[mid], block)
        return results

    # ----------------------------------------------- bf16 verification

    def verify_quantized(self, model_id, rows) -> dict:
        """Pin the bf16 fast path against the f32 oracle on a probe
        batch: labels must be BIT-EQUAL (argmin is ordering-robust
        where distances round — ties are the only flip risk), distances
        compared by relative error.  Returns
        ``{"labels_equal", "label_mismatches", "dist_max_rel"}``; the
        acceptance tests assert ``labels_equal`` on separated data."""
        rm = self._rm(model_id)
        if rm.spec["family"] == "gmm":
            raise ValueError("verify_quantized applies to the K-Means "
                             "family bf16 assignment fast path")
        if mesh_shape(self.mesh)[1] != 1:
            raise ValueError(
                "verify_quantized requires a data-parallel mesh — the "
                "guarded bf16 assignment has no TP form (quantization "
                "is rejected under TP sharding)")
        block = self._validate(model_id, "predict", rows)
        # Probe WITHOUT touching the resident's live quantize flag —
        # concurrent queued traffic must keep its configured mode (and
        # its corrected_rows audit unpolluted, review finding).
        buf, m, B = self._stage(rm, block)
        chunk = self._serve_chunk(rm, B)
        model_shards = mesh_shape(self.mesh)[1]
        cents_dev = rm.model._cents_dev(self.mesh, model_shards)
        pts, _ = shard_points(buf, self.mesh, chunk)
        if rm.quantize == "pq":
            return self._verify_pq(rm, buf, chunk, m, B, cents_dev)
        lab_q, corrected = self._assign_bf16_guarded(
            rm, buf, pts, cents_dev, chunk, m)
        f32_mode = rm.model._mode(B, rm.spec["d"])
        # Probe traffic is tagged under its own label so dispatch-count
        # pins can tell verification from serving (dispatch-accounting
        # lint: every compiled call site routes through note_dispatch).
        note_dispatch("verify-quantized/f32-oracle")
        with obs_trace.span("dispatch", tag="verify-quantized/f32-oracle",
                            rows=m):
            lab_f = np.asarray(self._predict_fn(chunk, f32_mode)(
                shard_points(buf, self.mesh, chunk)[0], cents_dev,
                np.int32(m)))[:m]

        def _distances(tmode):
            tfn = kmeans_mod._STEP_CACHE.get_or_create(
                (self.mesh, chunk, tmode, "transform"),
                lambda: dist.make_transform_fn(
                    self.mesh, chunk_size=chunk, mode=tmode))
            note_dispatch("verify-quantized/transform")
            return np.asarray(tfn(
                shard_points(buf, self.mesh, chunk)[0],
                cents_dev))[:m, : rm.spec["k"]]

        dist_q = _distances("matmul_bf16")
        dist_f = _distances("matmul")
        mism = int(np.sum(lab_q != lab_f))
        # bf16's error model is ~2^-8 relative to the |x||c| product
        # magnitude (ops/assign.py) — near-zero distances carry
        # cancellation-AMPLIFIED relative error by construction, so the
        # honest normalization is each row's distance SCALE (its max
        # distance), not the individual (possibly ~0) entry.
        f64q = dist_q.astype(np.float64)
        f64f = dist_f.astype(np.float64)
        scale = np.maximum(np.max(np.abs(f64f), axis=1, keepdims=True),
                           np.finfo(np.float64).tiny)
        rel = np.abs(f64q - f64f) / scale
        return {"labels_equal": mism == 0,
                "label_mismatches": mism,
                # Rows the near-tie guard re-labeled at f32 for this
                # probe — the price of exactness (0 on separated data).
                "corrected_rows": corrected,
                "dist_max_rel": float(np.max(rel))}

    def _verify_pq(self, rm: ResidentModel, buf: np.ndarray, chunk: int,
                   m: int, B: int, cents_dev) -> dict:
        """``verify_quantized`` for a ``quantize='pq'`` resident: the
        ADC route vs the f32 TRUE-table oracle.  Unlike bf16 (exact by
        construction), PQ's labels may legitimately differ — the
        decoded table is an approximation of the true one — so
        ``label_mismatches`` here MEASURES the quantization error on
        the probe rather than pinning zero, and ``dist_max_rel`` is the
        decoded-vs-true distance residual under the same row-scale
        normalization as the bf16 probe."""
        lab_q, corrected = self._assign_pq(rm, buf, m)
        f32_mode = rm.model._mode(B, rm.spec["d"])
        note_dispatch("verify-quantized/f32-oracle")
        with obs_trace.span("dispatch", tag="verify-quantized/f32-oracle",
                            rows=m):
            lab_f = np.asarray(self._predict_fn(chunk, f32_mode)(
                shard_points(buf, self.mesh, chunk)[0], cents_dev,
                np.int32(m)))[:m]
        Q = np.asarray(buf[:m], np.float64)
        table = np.asarray(rm.model.centroids, np.float64)
        decoded = rm.pq.decode(rm.pq_codes)

        def _d2(tab):
            return (np.sum(Q ** 2, axis=1)[:, None] - 2.0 * Q @ tab.T
                    + np.sum(tab ** 2, axis=1)[None, :])

        df, dq = _d2(table), _d2(decoded)
        scale = np.maximum(np.max(np.abs(df), axis=1, keepdims=True),
                           np.finfo(np.float64).tiny)
        mism = int(np.sum(lab_q != lab_f))
        return {"labels_equal": mism == 0,
                "label_mismatches": mism,
                "corrected_rows": int(corrected),
                "dist_max_rel": float(np.max(np.abs(dq - df) / scale))}

    # ------------------------------------------------------------ stats

    def warmup(self, model_id=None, *, buckets=None) -> int:
        """Pre-compile the predict path for each bucket shape (cold
        compiles otherwise land on the first unlucky request).  Returns
        the number of warm dispatches run (counted separately from
        serving stats)."""
        ids = [model_id] if model_id is not None else self.models()
        buckets = self.buckets if buckets is None else \
            check_buckets(buckets)
        n = 0
        # The _tls.warming flag (checked in _record and the bf16 audit
        # increment) keeps these probes out of the serving stats without
        # a counter rollback — concurrent requests on other threads keep
        # recording normally.
        self._tls.warming = True
        try:
            for mid in ids:
                rm = self._rm(mid)
                for B in buckets:
                    probe = np.zeros((B, rm.spec["d"]),
                                     np.dtype(rm.spec["dtype"]))
                    probe[:, 0] = 1.0       # finite, unit rows
                    self._dispatch(mid, "predict",
                                   self._validate(mid, "predict", probe))
                    n += 1
        finally:
            self._tls.warming = False
        return n

    def stats(self) -> dict:
        """Operator-facing snapshot: models resident, dispatch counts,
        batch-fill histogram (the ``serve --json`` payload)."""
        with self._lock:
            fill = {
                int(b): {"dispatches": v[0], "rows": v[1],
                         "fill": round(v[1] / (v[0] * b), 4)
                         if v[0] else 0.0}
                for b, v in sorted(self._fill.items())}
            models = {
                mid: {"family": rm.spec["family"],
                      "model_class": rm.spec["model_class"],
                      "k": rm.spec["k"], "d": rm.spec["d"],
                      "dtype": rm.spec["dtype"],
                      "quantize": rm.quantize,
                      "requests": rm.requests, "rows": rm.rows,
                      "dispatches": rm.dispatches,
                      "table_bytes": rm.table_bytes,
                      "bf16_corrected_rows": rm.bf16_corrected_rows,
                      "pq_corrected_rows": rm.pq_corrected_rows}
                for mid, rm in sorted(self._residents.items())}
            stats = {
                "models_resident": len(models),
                "models": models,
                "resident_table_bytes": sum(
                    m["table_bytes"] for m in models.values()),
                "program_memory": self._program_memory(),
                "dispatches": self.dispatches,
                "packed_dispatches": self.packed_dispatches,
                "queue": self.queue.stats(),
                "batch_fill": fill,
                "buckets": list(self.buckets),
            }
        # Quality block (ISSUE 14) assembled OUTSIDE the engine lock:
        # each monitor takes its own lock, and nesting them under the
        # engine's would order-couple dispatch and stats paths.
        stats["quality"] = self.quality_status()
        if self._learn_cfg is not None:
            stats["learn"] = self.update_status()
        return stats

    def update_status(self) -> dict:
        """Per-model serve-and-learn snapshot (ISSUE 20): armed state,
        budgets left, reservoir fill, pending evaluation, and the
        recent decision log.  ``{model_id: None}`` entries mean the
        model is not update-eligible (or learning is off).  Assembled
        outside the engine lock — each learner takes its own state
        lock, same discipline as ``quality_status``."""
        return {mid: (rm.learner.status() if rm.learner is not None
                      else None)
                for mid, rm in sorted(self._residents.items())}

    def quality_status(self) -> dict:
        """Per-model drift-monitor snapshot (the ``stats()`` quality
        block and the serve CLI's ``{"quality": true}`` payload);
        ``{model_id: None}`` entries mean monitoring is off."""
        return {mid: (rm.monitor.status() if rm.monitor is not None
                      else None)
                for mid, rm in sorted(self._residents.items())}

    #: Step caches serving dispatches compile through — the K-Means
    #: family's assignment/transform programs AND the mixture family's
    #: posterior/score programs (``_dispatch_gmm`` -> ``model
    #: ._posterior`` -> ``gmm._STEP_CACHE``).
    _SERVING_CACHES = ("kmeans._STEP_CACHE", "gmm._STEP_CACHE")

    @classmethod
    def _program_memory(cls) -> List[dict]:
        """Per-program compiled memory of the serving step caches
        (ISSUE 12 serving residency report): one compact row per
        :class:`~kmeans_tpu.obs.cost.CostRecord` captured from a
        ``_SERVING_CACHES`` cache while a cost collector is active —
        run ``warmup()`` (the per-bucket compiles) inside
        ``obs.cost.collecting()`` to populate it.  Empty when capture
        is off: residency bytes above stay available either way."""
        from kmeans_tpu.obs import cost as obs_cost
        col = obs_cost.get_collector()
        if col is None:
            return []
        return [{"cache": r.cache, "key": r.key, "role": r.role,
                 "peak_bytes": r.peak_bytes, "arg_bytes": r.arg_bytes,
                 "temp_bytes": r.temp_bytes, "code_bytes": r.code_bytes,
                 "available": r.available}
                for r in col.records()
                if r.cache in cls._SERVING_CACHES]

    # -------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Drain the queue, join its worker, close the drift-monitor
        sinks (idempotent).  Learners close FIRST (joining any
        in-flight update) so an update thread can neither publish to a
        closing engine nor write to a closed sink."""
        for rm in list(self._residents.values()):
            if rm.learner is not None:
                rm.learner.close(join=True)
        self.queue.close()
        for rm in self._residents.values():
            if rm.monitor is not None:
                rm.monitor.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
