"""Online serving subsystem (ISSUE 6): resident warm-kernel model
server with micro-batching, multi-model residency, and a bf16
quantized-distance fast path.

Entry points:

* :class:`ServingEngine` — hold fitted models resident on the mesh and
  serve ``predict``/``transform``/``score``/``predict_proba`` with
  compile-once warm kernels (``serving.engine``).
* :class:`MicroBatchQueue` / :class:`ServingFuture` — Clipper-style
  adaptive micro-batching of concurrent small requests
  (``serving.batching``).
* :class:`ModelRegistry` — multi-model residency + checkpoint loading
  + same-shape pack groups (``serving.registry``).
* :class:`ServingFleet` — N replicated engines behind an SLO-aware
  router with admission control, load shedding, and pack-group-aware
  placement (``serving.fleet``, ISSUE 17).

CLI: ``python -m kmeans_tpu serve --model <ckpt> [--model <ckpt> ...]``
(stdin/JSONL request loop, no network dependency; ``--replicas N``
serves through an in-process fleet).  Benchmarks:
``BENCH_SERVE=1 python bench.py``, ``BENCH_FLEET=1 python bench.py``
and ``experiments/exp_serving_load.py``.
"""

from kmeans_tpu.serving.batching import (MicroBatchQueue,
                                         ServingClosedError,
                                         ServingFuture)
from kmeans_tpu.serving.engine import ResidentModel, ServingEngine
from kmeans_tpu.serving.fleet import (FleetFuture, FleetOverloadError,
                                      ReplicaDeadError, ServingFleet)
from kmeans_tpu.serving.registry import ModelRegistry, load_fitted

__all__ = ["ServingEngine", "ResidentModel", "MicroBatchQueue",
           "ServingFuture", "ServingClosedError", "ModelRegistry",
           "load_fitted", "ServingFleet", "FleetFuture",
           "FleetOverloadError", "ReplicaDeadError"]
