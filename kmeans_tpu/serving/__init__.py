"""Online serving subsystem (ISSUE 6): resident warm-kernel model
server with micro-batching, multi-model residency, and a bf16
quantized-distance fast path.

Entry points:

* :class:`ServingEngine` — hold fitted models resident on the mesh and
  serve ``predict``/``transform``/``score``/``predict_proba`` with
  compile-once warm kernels (``serving.engine``).
* :class:`MicroBatchQueue` / :class:`ServingFuture` — Clipper-style
  adaptive micro-batching of concurrent small requests
  (``serving.batching``).
* :class:`ModelRegistry` — multi-model residency + checkpoint loading
  + same-shape pack groups (``serving.registry``).
* :class:`ServingFleet` — N replicated engines behind an SLO-aware
  router with admission control, load shedding, and pack-group-aware
  placement (``serving.fleet``, ISSUE 17).
* :class:`ModelLearner` / :func:`publish_tables` — serve-and-learn
  actuator (``serving.learn``, ISSUE 20): drift-triggered in-place
  ``partial_fit`` updates with snapshot-before-update, one atomic
  table swap, and rollback-on-regression, enabled via
  ``ServingEngine(learn=...)`` / ``ServingFleet(learn=...)``.

CLI: ``python -m kmeans_tpu serve --model <ckpt> [--model <ckpt> ...]``
(stdin/JSONL request loop, no network dependency; ``--replicas N``
serves through an in-process fleet).  Benchmarks:
``BENCH_SERVE=1 python bench.py``, ``BENCH_FLEET=1 python bench.py``
and ``experiments/exp_serving_load.py``.
"""

from kmeans_tpu.serving.batching import (MicroBatchQueue,
                                         ServingClosedError,
                                         ServingFuture)
from kmeans_tpu.serving.engine import ResidentModel, ServingEngine
from kmeans_tpu.serving.fleet import (FleetFuture, FleetOverloadError,
                                      ReplicaDeadError, ServingFleet)
from kmeans_tpu.serving.learn import (ModelLearner, UpdateRolledBack,
                                      publish_tables)
from kmeans_tpu.serving.registry import ModelRegistry, load_fitted

__all__ = ["ServingEngine", "ResidentModel", "MicroBatchQueue",
           "ServingFuture", "ServingClosedError", "ModelRegistry",
           "load_fitted", "ServingFleet", "FleetFuture",
           "FleetOverloadError", "ReplicaDeadError", "ModelLearner",
           "UpdateRolledBack", "publish_tables"]
