"""CLI entry point: ``python -m kmeans_tpu <command>``.

The reference has no CLI layer (SURVEY.md §1: no argparse, the ``__main__``
block takes no arguments); this is a thin superset exposing the narrative
suite and the benchmark harness.
"""

import sys


def main() -> int:
    args = sys.argv[1:]
    cmd = args[0] if args and not args[0].startswith("-") else "suite"
    rest = args[1:] if args and not args[0].startswith("-") else args
    if cmd == "suite":
        from kmeans_tpu.suite import main as suite_main
        return suite_main(rest)
    if cmd == "bench":
        from kmeans_tpu.benchmarks import main as bench_main
        return bench_main(rest)
    if cmd == "fit":
        from kmeans_tpu.cli import main as fit_main
        return fit_main(rest)
    if cmd == "sweep":
        from kmeans_tpu.cli import sweep_main
        return sweep_main(rest)
    if cmd == "ckpt-info":
        from kmeans_tpu.cli import ckpt_info_main
        return ckpt_info_main(rest)
    if cmd == "warm":
        from kmeans_tpu.cli import warm_main
        return warm_main(rest)
    if cmd == "serve":
        from kmeans_tpu.cli import serve_main
        return serve_main(rest)
    if cmd == "report":
        from kmeans_tpu.utils.diagram import main as report_main
        return report_main(rest)
    if cmd == "lint":
        from kmeans_tpu.cli import lint_main
        return lint_main(rest)
    if cmd == "trace":
        from kmeans_tpu.cli import trace_main
        return trace_main(rest)
    if cmd == "cost-report":
        from kmeans_tpu.cli import cost_report_main
        return cost_report_main(rest)
    if cmd == "fleet-status":
        from kmeans_tpu.cli import fleet_status_main
        return fleet_status_main(rest)
    if cmd == "serve-status":
        from kmeans_tpu.cli import serve_status_main
        return serve_status_main(rest)
    if cmd == "bench-diff":
        from kmeans_tpu.cli import bench_diff_main
        return bench_diff_main(rest)
    if cmd == "plan":
        from kmeans_tpu.cli import plan_main
        return plan_main(rest)
    if cmd == "autopilot":
        from kmeans_tpu.cli import autopilot_main
        return autopilot_main(rest)
    print(f"unknown command {cmd!r}; available: suite, bench, fit, "
          f"sweep, ckpt-info, warm, serve, report, lint, trace, "
          f"cost-report, fleet-status, serve-status, bench-diff, plan, "
          f"autopilot",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
