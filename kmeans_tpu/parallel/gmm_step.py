"""The SPMD EM machinery for diagonal-covariance Gaussian mixtures.

Same execution model as the K-Means step (``distributed.make_step_fn``):
points sharded on the ``data`` mesh axis, one jitted ``shard_map`` whose
collectives are a ``psum`` of dense per-component accumulators (plus,
under component sharding, a per-chunk ``pmax``/``psum`` pair for the
softmax normalizer).  The reference framework has no mixture model at
all — this is a beyond-reference family built on the same TPU-first
machinery (SURVEY.md §2.3 backend mapping).

TPU formulation of the E-step: for diagonal Gaussians,

    log N(x | mu_k, sigma_k^2)
      = -0.5 * [ sum_d x_d^2 * a_kd  -  2 sum_d x_d * (mu_kd * a_kd)
                 + sum_d mu_kd^2 * a_kd + sum_d log sigma_kd^2
                 + D * log 2pi ]                    with a = 1/sigma^2,

so the (chunk, k) log-density tile is TWO matmuls — ``x^2 @ a.T`` and
``x @ (mu*a).T`` — plus per-component row constants: the same
MXU-dominant shape as the K-Means distance pass.  Responsibilities come
from a max-subtracted softmax over k; the per-chunk accumulators

    R_k    = sum_i w_i r_ik                       (k,)
    S1_k   = sum_i w_i r_ik x_i                   (k, D)  [resp.T @ x]
    S2_k   = sum_i w_i r_ik x_i^2                 (k, D)  [resp.T @ x^2]
    ll     = sum_i w_i logsumexp_k(...)           ()

are all dense and psum-able; the M-step (host or device side) is then
pi = R/W, mu = S1/R, sigma^2 = S2/R - mu^2 + reg.  Zero-weight padding
rows contribute nothing to any statistic.

Centering (``shift``): every pass subtracts a caller-supplied (D,)
shift — the data's global mean — from each chunk and works against
SHIFTED means.  Responsibilities and the log-likelihood are exactly
shift-invariant, but the accumulated E-statistics are not numerically:
the uncentered ``S2/R - mu^2`` cancels below f32 precision when
``|mean|/std >~ 1e3`` and covariances silently collapse to the
``reg_covar`` clamp (r2 ADVICE, medium).  Accumulating in the centered
frame keeps ``S2`` at the data's SPREAD scale, so the variance emerges
without cancellation; the caller adds ``shift`` back to the means.  The
subtract fuses into the chunk pipeline — no centered copy of the data is
ever materialized.

Component (model-axis) sharding: the (k, D) parameter tables row-shard
over the ``model`` axis exactly like the K-Means centroid table.  Each
shard scores points against its component block; the softmax normalizer
needs the GLOBAL max and sum over k, reconstructed with one ``pmax`` and
one ``psum`` of (chunk,) vectors per chunk — O(chunk) traffic against
the O(chunk*k_local) matmul tile, negligible on ICI.  Per-shard
statistics cover the local block and are embedded + psum'd like the
K-Means step.  Component padding rows (k not divisible by the axis)
carry ``log_weights = -inf`` so they never receive responsibility.

Software-pipelined E pass (``pipeline=1``, the builder default —
``GaussianMixture(pipeline='auto')`` resolves it per platform; ISSUE
3): the
serial chunk body runs four phases — two log-density matmuls (MXU),
the max-subtracted exp/softmax (VPU transcendentals), and two moment
matmuls (MXU) — strictly in sequence, so the MXU idles while the
(chunk, k) softmax burns one ``exp`` per point-component pair (~33% MFU
at 2M x 128 k=256, docs/PERFORMANCE.md "The mixture family").  The
pipelined schedule skews the scan one chunk: each ``lax.scan`` step
computes chunk i's log-density matmuls (stage A) while CONSUMING chunk
i-1's carried logp tile — softmax + moment matmuls (stage B) — so the
two stages have no data dependency inside a step and XLA's scheduler is
free to overlap stage B's VPU transcendentals with stage A's MXU
matmuls (the online-softmax stage-overlap discipline of the
flash-attention literature, applied at chunk rather than tile
granularity; no Pallas needed).  The carry holds one in-flight
(chunk, k_local) logp tile plus the centered chunk (HBM-resident
between steps — the double-buffer cost the chunk-size sweep re-prices,
``EM_MAX_CHUNK``).  Per chunk the ARITHMETIC is identical to the serial
body, and chunk statistics fold in the same order, so ``pipeline=0`` is
the bit-exact parity oracle (the ``prefetch=0`` discipline of r6).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kmeans_tpu.obs import trace as _obs_trace
from kmeans_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, mesh_shape,
                                      shard_map)

_LOG2PI = math.log(2.0 * math.pi)


class EStats(NamedTuple):
    """Globally-reduced E-step statistics (everything psum-able)."""

    resp_sum: jax.Array    # (k,)   sum of weighted responsibilities
    xsum: jax.Array        # (k, D) responsibility-weighted point sums
    x2sum: jax.Array       # (k, D) responsibility-weighted square sums
    loglik: jax.Array      # ()     weighted total log-likelihood


def _log_prob_chunk(x, means, inv_var, log_det, log_weights):
    """(chunk, k) weighted log joint: log pi_k + log N(x | mu_k, s2_k)."""
    a = inv_var                                    # (k, D)
    b = means * inv_var                            # (k, D)
    x2a = lax.dot_general(x * x, a, (((1,), (1,)), ((), ())),
                          preferred_element_type=x.dtype)   # (c, k) MXU
    xb = lax.dot_general(x, b, (((1,), (1,)), ((), ())),
                         preferred_element_type=x.dtype)    # (c, k) MXU
    quad = x2a - 2.0 * xb + jnp.sum(means * b, axis=1)[None, :]
    d = x.shape[1]
    return (log_weights[None, :]
            - 0.5 * (quad + log_det[None, :] + d * _LOG2PI))


def _diag_stage_fns(means, inv_var, log_det, log_weights,
                    model_shards: int, acc, exp_dtype=None):
    """The diag/spherical E pass split into its two pipeline stages —
    the ONE implementation of this arithmetic (``_estep_tile`` and the
    chunked scans both call it, so the hard-won precision rules below
    cannot drift between the oracle and the scan bodies).

    ``logp_fn`` is stage A (the two MXU log-density matmuls);
    ``consume`` is stage B: the shared cross-model-axis softmax plus
    the moment accumulators.  Moments run at HIGH matmul precision: on
    TPU, "f32" dots execute with bf16-rounded products by default (fine
    for the responsibility softmax — relative logp error ~2^-8 barely
    moves a softmax), but the M-step's variance is the DIFFERENCE
    S2/R - mu^2, which survives only while |mu|/sigma < ~sqrt(2^8) ~ 16
    per dim under bf16 products.  Clusters offset ~25 sigma from the
    global mean collapsed to reg_covar on hardware (r3, found driving
    the v5e; invisible on CPU where f32 dots are exact).  r3 pinned
    HIGHEST (the 6-pass bf16_6x split ~ true f32); the r5 precision
    ladder (experiments/exp_gmm_estep_retry.py, real v5e) measured
    HIGH (the 3-pass bf16_3x split) INDISTINGUISHABLE from HIGHEST on
    the r3 failure shape (25+ sigma offsets: max relative variance
    error 3.024e-2 vs 3.024e-2 — the probe's own sampling noise)
    while cutting the full E-pass 13.79 -> 9.01 ms at 2M x 128 k=256
    (20 -> 31% MFU); DEFAULT (one bf16-product pass) degrades the
    probe to 4.1e-2 and stays rejected.  HIGH it is — for the two
    moment matmuls only."""
    hi = lax.Precision.HIGH

    def logp_fn(xc):
        return _log_prob_chunk(xc, means, inv_var, log_det, log_weights)

    def consume(carry, logp, xc, wc):
        resp, lse = _softmax_resp(logp, wc, model_shards,
                                  exp_dtype=exp_dtype)
        return EStats(
            carry.resp_sum + jnp.sum(resp, axis=0),
            carry.xsum + lax.dot_general(
                resp, xc, (((0,), (0,)), ((), ())),
                preferred_element_type=acc, precision=hi),
            carry.x2sum + lax.dot_general(
                resp, xc * xc, (((0,), (0,)), ((), ())),
                preferred_element_type=acc, precision=hi),
            carry.loglik + jnp.sum(jnp.where(wc > 0, lse * wc, 0.0)))

    return logp_fn, consume


def _zero_estats(k_local: int, d: int, acc) -> EStats:
    return EStats(jnp.zeros((k_local,), acc),
                  jnp.zeros((k_local, d), acc),
                  jnp.zeros((k_local, d), acc), jnp.zeros((), acc))


def _estep_tile(x, w, means, inv_var, log_det, log_weights,
                model_shards: int):
    """One chunk's LOCAL-block contribution to EStats.  With the component
    table sharded, the softmax normalizer (row max + denominator) is
    reconstructed globally via pmax/psum over the model axis; the
    statistics stay local to this shard's block.  ``loglik`` is identical
    on every model shard (the caller divides the cross-axis psum out).
    Exactly the shared stage pair applied to one chunk and a zero
    carry (``_diag_stage_fns``)."""
    k, d = means.shape
    acc = x.dtype
    logp_fn, consume = _diag_stage_fns(means, inv_var, log_det,
                                       log_weights, model_shards, acc)
    return consume(_zero_estats(k, d, acc), logp_fn(x), x, w)


def estep_chunk(x, w, means, inv_var, log_det, log_weights):
    """Unsharded one-chunk E-statistics (oracle tests use this)."""
    return _estep_tile(x, w, means, inv_var, log_det, log_weights, 1)


def _chunked_epass(points, weights, shift, *, chunk_size: int,
                   pipeline: int, logp_fn, consume_fn, init, acc):
    """The shared chunk loop of every covariance type's E pass.

    ``logp_fn(xc) -> (chunk, k_local) logp`` is stage A (the MXU
    log-density matmuls); ``consume_fn(stats, logp, xc, wc) -> stats``
    is stage B (softmax + moment accumulation).  ``xc`` arrives already
    centered by ``shift``.

    ``pipeline=0`` runs A and B back-to-back per chunk (the serial
    four-phase body — the parity oracle).  ``pipeline=1`` skews the
    schedule one chunk: a prologue computes chunk 0's logp outside the
    scan, each scan step then runs stage A for chunk i and stage B for
    chunk i-1 (no data dependency between the two inside a step, so XLA
    can overlap the VPU softmax with the next chunk's MXU matmuls), and
    an epilogue drains the final in-flight chunk.  Per chunk the
    arithmetic and the fold order of the statistics are IDENTICAL to
    the serial body — the schedules are bit-exact parity partners
    (pinned, tests/test_gmm_pipeline.py)."""
    d = points.shape[1]
    n_chunks = points.shape[0] // chunk_size
    xs = (points.reshape(n_chunks, chunk_size, d),
          weights.astype(acc).reshape(n_chunks, chunk_size))

    if not pipeline:
        def body(carry, chunk):
            xc_raw, wc = chunk
            xc = xc_raw - shift[None, :]
            return consume_fn(carry, logp_fn(xc), xc, wc), None

        st, _ = lax.scan(body, init, xs)
        return st

    # Prologue: stage A for chunk 0 (fills the one-chunk logp buffer).
    x0 = xs[0][0] - shift[None, :]
    w0 = xs[1][0]
    rest = (xs[0][1:], xs[1][1:])

    def body(carry, chunk):
        st, logp_prev, x_prev, w_prev = carry
        xc_raw, wc = chunk
        xc = xc_raw - shift[None, :]
        logp_c = logp_fn(xc)                        # stage A, chunk i
        st = consume_fn(st, logp_prev, x_prev, w_prev)   # stage B, i-1
        return (st, logp_c, xc, wc), None

    (st, logp_last, x_last, w_last), _ = lax.scan(
        body, (init, logp_fn(x0), x0, w0), rest)
    # Epilogue: stage B for the final in-flight chunk.
    return consume_fn(st, logp_last, x_last, w_last)


def _scan_estats(points, weights, means_blk, inv_var_blk, log_det_blk,
                 log_w_blk, shift, *, chunk_size: int, model_shards: int,
                 pipeline: int = 1, exp_dtype=None):
    """Shard-local chunked E pass -> local-block EStats (pre-psum).
    ``shift`` centers each chunk in registers; ``means_blk`` must already
    be in the centered frame.  ``pipeline`` selects the chunk schedule
    (see ``_chunked_epass``); ``exp_dtype`` the responsibility-exp
    precision rung (see ``_softmax_resp``)."""
    k_local, d = means_blk.shape
    acc = points.dtype
    # The stage pair (and the HIGH moment-precision rationale) lives in
    # _diag_stage_fns, shared with the _estep_tile oracle.
    logp_fn, consume = _diag_stage_fns(means_blk, inv_var_blk,
                                       log_det_blk, log_w_blk,
                                       model_shards, acc,
                                       exp_dtype=exp_dtype)
    return _chunked_epass(points, weights, shift, chunk_size=chunk_size,
                          pipeline=pipeline, logp_fn=logp_fn,
                          consume_fn=consume,
                          init=_zero_estats(k_local, d, acc), acc=acc)


def _embed_psum(st: EStats, k_pad: int, k_local: int, model_shards: int):
    """Embed a shard's local-block stats into the full table and psum over
    both axes -> replicated global EStats (the K-Means embedding pattern,
    distributed.make_step_fn)."""
    d = st.xsum.shape[1]
    acc = st.xsum.dtype
    m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
    off = jnp.asarray(m_idx * k_local, jnp.int32)
    axes = (DATA_AXIS, MODEL_AXIS)
    resp = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad,), acc), st.resp_sum, (off,)), axes)
    xsum = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad, d), acc), st.xsum, (off, jnp.int32(0))), axes)
    x2sum = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad, d), acc), st.x2sum, (off, jnp.int32(0))), axes)
    # loglik is replicated across the model axis -> divide the psum out.
    ll = lax.psum(st.loglik, axes) / model_shards
    return EStats(resp, xsum, x2sum, ll)


@_obs_trace.traced_builder
def make_gmm_step_fn(mesh: Mesh, *, chunk_size: int, pipeline: int = 1,
                     exp_dtype=None) -> Callable:
    """Build the jitted SPMD E-step:
    (points, weights, shift, means, inv_var, log_det, log_weights) ->
    EStats over the FULL (k_pad) component table, replicated.  Parameter
    tables arrive row-sharded on the ``model`` axis (replicated when that
    axis is 1); ``means`` must be pre-centered by ``shift`` and the
    returned ``xsum``/``x2sum`` are in the centered frame.
    ``pipeline``/``exp_dtype`` select the chunk schedule and the
    responsibility-exp precision rung (``_chunked_epass`` /
    ``_softmax_resp``)."""
    data_shards, model_shards = mesh_shape(mesh)

    def step(points, weights, shift, means, inv_var, log_det, log_weights):
        k_local = means.shape[0]
        st = _scan_estats(points, weights, means, inv_var, log_det,
                          log_weights, shift, chunk_size=chunk_size,
                          model_shards=model_shards, pipeline=pipeline,
                          exp_dtype=exp_dtype)
        return _embed_psum(st, k_local * model_shards, k_local,
                           model_shards)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(MODEL_AXIS, None), P(MODEL_AXIS, None), P(MODEL_AXIS),
                  P(MODEL_AXIS)),
        out_specs=EStats(P(None), P(None, None), P(None, None), P()),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_gmm_predict_fn(mesh: Mesh, *, chunk_size: int) -> Callable:
    """Jitted sharded posterior pass:
    (points, shift, means, inv_var, log_det, log_weights) ->
    (labels, log_resp (n, k_pad), log_prob (n,)).  Labels are GLOBAL
    component indices (under component sharding each shard's local argmax
    is promoted by the gathered per-block maxima, lowest block wins
    ties); ``log_resp`` comes back sharded (data, model) so no device
    ever holds more than its (n_local, k_local) tile."""
    data_shards, model_shards = mesh_shape(mesh)

    def predict(points, shift, means, inv_var, log_det, log_weights):
        k_local, d = means.shape
        return _predict_from_logp(
            lambda xc: _log_prob_chunk(
                xc - shift[None, :], means, inv_var, log_det,
                log_weights),
            points, chunk_size, k_local, d, model_shards)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None), P(MODEL_AXIS, None),
                  P(MODEL_AXIS, None), P(MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


class EStatsFull(NamedTuple):
    """Globally-reduced E-step statistics for FULL covariances: the diag
    ``x2sum`` is replaced by the per-component scatter moment
    ``sum_i w_i r_ik (x_i - shift)(x_i - shift)^T`` — one dense
    psum-reducible (k, D, D) tensor accumulated as batched outer-product
    matmuls on the MXU (r3 VERDICT #5)."""

    resp_sum: jax.Array    # (k,)
    xsum: jax.Array        # (k, D)
    scatter: jax.Array     # (k, D, D)
    loglik: jax.Array      # ()


def _log_prob_full_chunk(x, means, prec_chol, log_det_half, log_weights):
    """(chunk, k) weighted log joint for full covariances.

    ``prec_chol`` is the precision Cholesky P_k = L_k^-T (sklearn's
    parameterization: Sigma_k = L_k L_k^T, Sigma_k^-1 = P_k P_k^T), so

        log N(x | mu_k, Sigma_k)
          = log_det_half_k - 0.5 * (||(x - mu_k) P_k||^2 + D log 2pi)

    with ``log_det_half_k = sum_d log P_k[d, d]`` (= -0.5 log|Sigma_k|).
    The transform is ONE batched (chunk, D) x (k, D, D) einsum — k
    matmuls on the MXU — minus a per-component constant row."""
    xt = jnp.einsum("cd,kde->cke", x, prec_chol,
                    preferred_element_type=x.dtype)        # (c, k, D)
    mt = jnp.einsum("kd,kde->ke", means, prec_chol,
                    preferred_element_type=x.dtype)        # (k, D)
    quad = jnp.sum((xt - mt[None]) ** 2, axis=-1)          # (c, k)
    d = x.shape[1]
    return (log_weights[None, :] + log_det_half[None, :]
            - 0.5 * (quad + d * _LOG2PI))


def _log_prob_tied_chunk(x, means_t, prec_chol, log_det_half, log_weights):
    """(chunk, k) weighted log joint for a TIED covariance: with ONE
    shared precision Cholesky P, transform once (``xt = x @ P`` — a
    single matmul) and the quadratic form becomes the SAME
    ``||xt||^2 + ||mt||^2 - 2 xt mt^T`` two-matmul MXU shape as the
    diagonal density.  ``means_t`` must be pre-transformed (mu @ P)."""
    xt = x @ prec_chol                                     # (c, D) MXU
    x2 = jnp.sum(xt * xt, axis=1)[:, None]
    cross = lax.dot_general(xt, means_t, (((1,), (1,)), ((), ())),
                            preferred_element_type=x.dtype)  # (c, k) MXU
    m2 = jnp.sum(means_t * means_t, axis=1)[None, :]
    quad = x2 - 2.0 * cross + m2
    d = x.shape[1]
    return (log_weights[None, :] + log_det_half
            - 0.5 * (quad + d * _LOG2PI))


def _softmax_resp(logp, w, model_shards: int, exp_dtype=None):
    """Shared responsibility softmax with the cross-model-axis
    normalizer reconstruction; returns (resp, lse).

    ``exp_dtype`` is the responsibility-exp precision rung (ISSUE 3):
    when set (bf16 is the candidate), the max-subtracted argument is
    rounded to that dtype before ``exp`` and the result widened back —
    post-subtraction the argument is <= 0 and the module's own analysis
    says relative logp error ~2^-8 "barely moves a softmax", but per
    repo discipline the rung is DEFAULT-OFF until the 25-sigma survival
    probe (experiments/exp_gmm_exp_precision.py, decision rules
    committed in the script) and a hardware timing gate adopt it; the
    normalizer sum/divide stay in the accumulation dtype either way."""
    m = jnp.max(logp, axis=1)
    if model_shards > 1:
        m = lax.pmax(m, MODEL_AXIS)
    z = logp - m[:, None]
    if exp_dtype is not None:
        p = jnp.exp(z.astype(exp_dtype)).astype(logp.dtype)
    else:
        p = jnp.exp(z)
    denom = jnp.sum(p, axis=1)
    if model_shards > 1:
        denom = lax.psum(denom, MODEL_AXIS)
    lse = m + jnp.log(denom)
    return p / denom[:, None] * w[:, None], lse


def _scan_estats_full(points, weights, means, prec_chol, log_det_half,
                      log_w, shift, *, chunk_size: int,
                      model_shards: int, pipeline: int = 1,
                      exp_dtype=None) -> EStatsFull:
    """Shard-local chunked FULL-covariance E pass -> local-block
    EStatsFull (pre-psum).  Shared by the per-dispatch step builder and
    the on-device fit loop.  ``pipeline``/``exp_dtype`` as in
    ``_scan_estats``."""
    k_local, d = means.shape
    acc = points.dtype
    # HIGH, not HIGHEST, for the xsum/scatter moments: the r5 FULL-
    # covariance precision ladder (experiments/exp_gmm_full_precision.py,
    # real v5e) measured HIGH at HIGHEST-equivalent error on the 25-sigma
    # survival probe (diag 2.5e-2 vs 2.1e-2, offdiag 2.3e-2 vs 2.4e-2 —
    # the probe's own noise scale, both far under the 5% bar) and 1.53x
    # faster per E-pass (27.5 -> 18.0 ms at 1M x 64 k=32).  DEFAULT also
    # passed THIS probe but is kept rejected for consistency with the
    # diag ladder, where it showed real degradation.
    hi = lax.Precision.HIGH

    def logp_fn(xc):
        return _log_prob_full_chunk(xc, means, prec_chol, log_det_half,
                                    log_w)

    def consume(carry, logp, xc, wc):
        resp, lse = _softmax_resp(logp, wc, model_shards,
                                  exp_dtype=exp_dtype)
        return EStatsFull(
            carry.resp_sum + jnp.sum(resp, axis=0),
            carry.xsum + lax.dot_general(
                resp, xc, (((0,), (0,)), ((), ())),
                preferred_element_type=acc, precision=hi),
            carry.scatter + jnp.einsum(
                "ck,cd,ce->kde", resp, xc, xc,
                preferred_element_type=acc, precision=hi),
            carry.loglik + jnp.sum(jnp.where(wc > 0, lse * wc, 0.0)))

    init = EStatsFull(jnp.zeros((k_local,), acc),
                      jnp.zeros((k_local, d), acc),
                      jnp.zeros((k_local, d, d), acc),
                      jnp.zeros((), acc))
    return _chunked_epass(points, weights, shift, chunk_size=chunk_size,
                          pipeline=pipeline, logp_fn=logp_fn,
                          consume_fn=consume, init=init, acc=acc)


def _embed_psum_full(st: EStatsFull, k_pad: int, k_local: int,
                     model_shards: int) -> EStatsFull:
    """Embed a shard's local-block FULL stats into the padded table and
    psum over both axes (the K-Means embedding pattern)."""
    d = st.xsum.shape[1]
    acc = st.xsum.dtype
    m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
    off = jnp.asarray(m_idx * k_local, jnp.int32)
    axes = (DATA_AXIS, MODEL_AXIS)
    resp = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad,), acc), st.resp_sum, (off,)), axes)
    xsum = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad, d), acc), st.xsum, (off, jnp.int32(0))), axes)
    scatter = lax.psum(lax.dynamic_update_slice(
        jnp.zeros((k_pad, d, d), acc), st.scatter,
        (off, jnp.int32(0), jnp.int32(0))), axes)
    ll = lax.psum(st.loglik, axes) / model_shards
    return EStatsFull(resp, xsum, scatter, ll)


def _prec_chol_dev(cov, tiny):
    """On-device precision Cholesky of a (..., D, D) covariance batch:
    Sigma = L L^T -> P = L^-T, log_det_half = -sum log diag L.  A
    non-PD input yields NaNs, which surface as a non-finite
    log-likelihood (the device loop's loud-failure contract)."""
    from jax.scipy.linalg import solve_triangular
    d = cov.shape[-1]
    L = jnp.linalg.cholesky(cov)
    eye = jnp.broadcast_to(jnp.eye(d, dtype=cov.dtype), cov.shape)
    p_chol = jnp.swapaxes(
        solve_triangular(L, eye, lower=True), -1, -2)
    ldh = -jnp.sum(jnp.log(jnp.maximum(
        jnp.diagonal(L, axis1=-2, axis2=-1), tiny)), axis=-1)
    return p_chol, ldh


@_obs_trace.traced_builder
def make_gmm_step_full_fn(mesh: Mesh, *, chunk_size: int,
                          pipeline: int = 1, exp_dtype=None) -> Callable:
    """Full-covariance SPMD E-step: (points, weights, shift, means_c,
    prec_chol (k, D, D), log_det_half (k,), log_weights) -> EStatsFull
    replicated.  Parameter tables row-shard on the ``model`` axis
    (components); the xsum/scatter moments accumulate at HIGH matmul
    precision — raised above the bf16 default for the same cancellation
    reason as the diag moments, relaxed from r3's HIGHEST by the r5
    full-covariance precision ladder (see _scan_estats_full).
    ``pipeline``/``exp_dtype`` as in ``make_gmm_step_fn``."""
    data_shards, model_shards = mesh_shape(mesh)

    def step(points, weights, shift, means, prec_chol, log_det_half,
             log_weights):
        k_local, d = means.shape
        st = _scan_estats_full(points, weights, means, prec_chol,
                               log_det_half, log_weights, shift,
                               chunk_size=chunk_size,
                               model_shards=model_shards,
                               pipeline=pipeline, exp_dtype=exp_dtype)
        return _embed_psum_full(st, k_local * model_shards, k_local,
                                model_shards)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(MODEL_AXIS, None), P(MODEL_AXIS, None, None),
                  P(MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=EStatsFull(P(None), P(None, None),
                             P(None, None, None), P()),
        check_vma=False)
    return jax.jit(mapped)


def _scan_estats_tied(points, weights, means_t, prec_chol, log_det_half,
                      log_w, shift, *, chunk_size: int,
                      model_shards: int, pipeline: int = 1,
                      exp_dtype=None) -> EStats:
    """Shard-local chunked TIED-covariance E pass -> local-block EStats
    with ``x2sum`` elided (the tied M-step derives its covariance from
    the loop-invariant total scatter + means).  Shared by the
    per-dispatch step builder and the on-device fit loop.
    ``pipeline``/``exp_dtype`` as in ``_scan_estats``."""
    k_local, d = means_t.shape
    acc = points.dtype
    hi = lax.Precision.HIGHEST

    def logp_fn(xc):
        return _log_prob_tied_chunk(xc, means_t, prec_chol,
                                    log_det_half, log_w)

    def consume(carry, logp, xc, wc):
        resp, lse = _softmax_resp(logp, wc, model_shards,
                                  exp_dtype=exp_dtype)
        return EStats(
            carry.resp_sum + jnp.sum(resp, axis=0),
            carry.xsum + lax.dot_general(
                resp, xc, (((0,), (0,)), ((), ())),
                preferred_element_type=acc, precision=hi),
            carry.x2sum,                # elided — not accumulated
            carry.loglik + jnp.sum(jnp.where(wc > 0, lse * wc, 0.0)))

    init = EStats(jnp.zeros((k_local,), acc),
                  jnp.zeros((k_local, d), acc),
                  jnp.zeros((k_local, d), acc), jnp.zeros((), acc))
    return _chunked_epass(points, weights, shift, chunk_size=chunk_size,
                          pipeline=pipeline, logp_fn=logp_fn,
                          consume_fn=consume, init=init, acc=acc)


@_obs_trace.traced_builder
def make_gmm_step_tied_fn(mesh: Mesh, *, chunk_size: int,
                          pipeline: int = 1, exp_dtype=None) -> Callable:
    """Tied-covariance SPMD E-step: (points, weights, shift, means_t
    (pre-transformed mu_c @ P), prec_chol (D, D) replicated,
    log_det_half (), log_weights) -> EStats replicated with ``x2sum``
    zero (the tied M-step derives the covariance from the loop-invariant
    total scatter + means, so no per-component second moment is
    accumulated).  ``pipeline``/``exp_dtype`` as in
    ``make_gmm_step_fn``."""
    data_shards, model_shards = mesh_shape(mesh)

    def step(points, weights, shift, means_t, prec_chol, log_det_half,
             log_weights):
        k_local = means_t.shape[0]
        st = _scan_estats_tied(points, weights, means_t, prec_chol,
                               log_det_half, log_weights, shift,
                               chunk_size=chunk_size,
                               model_shards=model_shards,
                               pipeline=pipeline, exp_dtype=exp_dtype)
        return _embed_psum(st, k_local * model_shards, k_local,
                           model_shards)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(MODEL_AXIS, None), P(None, None), P(), P(MODEL_AXIS)),
        out_specs=EStats(P(None), P(None, None), P(None, None), P()),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_total_scatter_fn(mesh: Mesh) -> Callable:
    """(points, weights, shift) -> (D, D) total weighted scatter
    ``sum_i w_i (x_i - shift)(x_i - shift)^T``, replicated — the
    loop-INVARIANT term of the tied M-step (computed once per fit)."""
    def total(points, weights, shift):
        xc = points - shift[None, :]
        w = weights.astype(points.dtype)
        t = lax.dot_general(xc * w[:, None], xc, (((0,), (0,)), ((), ())),
                            preferred_element_type=points.dtype,
                            precision=lax.Precision.HIGHEST)
        return lax.psum(t, DATA_AXIS)

    mapped = shard_map(
        total, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None)),
        out_specs=P(None, None), check_vma=False)
    return jax.jit(mapped)


def _predict_from_logp(logp_fn, points, chunk_size, k_local, d,
                       model_shards):
    """Shared posterior scan: per chunk compute logp via ``logp_fn``,
    reconstruct global labels/log-resp/lse across the model axis."""
    n_chunks = points.shape[0] // chunk_size
    xs = points.reshape(n_chunks, chunk_size, d)
    m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0

    def body(_, xc):
        logp = logp_fn(xc)
        best_l = jnp.argmax(logp, axis=1).astype(jnp.int32)
        max_l = jnp.max(logp, axis=1)
        if model_shards > 1:
            maxes = lax.all_gather(max_l, MODEL_AXIS)
            owner = jnp.argmax(maxes, axis=0)
            m_glob = jnp.max(maxes, axis=0)
            labels = lax.psum(
                jnp.where(owner == m_idx, m_idx * k_local + best_l, 0),
                MODEL_AXIS).astype(jnp.int32)
        else:
            m_glob, labels = max_l, best_l
        denom = jnp.sum(jnp.exp(logp - m_glob[:, None]), axis=1)
        if model_shards > 1:
            denom = lax.psum(denom, MODEL_AXIS)
        lse = m_glob + jnp.log(denom)
        return None, (labels, logp - lse[:, None], lse)

    _, (labels, logr, lse) = lax.scan(body, None, xs)
    return (labels.reshape(-1), logr.reshape(-1, k_local),
            lse.reshape(-1))


@_obs_trace.traced_builder
def make_gmm_multi_fit_fn(mesh: Mesh, *, chunk_size: int, k_real: int,
                          max_iter: int, tol: float, reg_covar: float,
                          cov_type: str = "diag", pipeline: int = 1,
                          k_reals=None, return_all: bool = False):
    """BATCHED on-device EM: ``n_init`` restarts in ONE dispatch, vmapped
    over the restart axis — the mixture analogue of
    ``distributed.make_multi_fit_fn`` (r4).  Works for the
    diag/spherical density (the restart axis batches the two log-density
    matmuls straight onto the MXU, raising utilization for small k).

    Restarts converge independently (frozen once |ll - prev| < tol);
    the winner is the restart with the HIGHEST final lower bound —
    sklearn's (and the host-sequential path's) selection rule, read
    from each restart's own last recorded lower bound, no extra pass.
    A DIVERGED restart (NaN log-likelihood — e.g. a collapsed component
    under reg_covar=0) surfaces as ``-inf`` in ``final_lls`` and can
    never win — the batched sweep keeps the sequential path's
    failed-restart resilience (r3 ADVICE); the caller raises only when
    every restart diverged.

    Returns ``fit(points, weights, shift, means0 (R, k_pad, D),
    var0 (R, k_pad, D), log_w0 (R, k_pad)) -> (means_c, var, log_w,
    n_iter, ll_hist[max_iter], converged, best, final_lls (R,))`` for
    the winning restart, everything replicated.

    ``k_reals`` (length-R, each <= ``k_real``) generalizes the member
    axis to a COMPONENT-COUNT sweep (ISSUE 7): member r's components
    beyond ``k_reals[r]`` must arrive as the r10 inert-pad constants
    (zero mean, unit variance, ``log_w = -inf``) — they receive zero
    responsibility, the per-member ``real`` mask keeps their parameters
    pinned at the pad constants through every M-step, and the weight
    renormalization sums only real components, so real-component
    arithmetic matches the standalone k_m fit to the documented GMM
    reduction class.  ``return_all=True`` hands every member's final
    state back for HOST-side selection (BIC/AIC, not the loop's ll):
    ``(means_c (R,k_pad,D), var (R,k_pad,D), log_w (R,k_pad), n_it (R,),
    ll_hist (R,max_iter), conv (R,), final_lls (R,), final_scores (R,))``
    where ``final_scores`` is one EXTRA vmapped E pass over the FINAL
    parameters — the same fresh-scoring quantity ``GaussianMixture.
    score``/``bic`` computes, which the in-loop ``final_lls`` (one
    M-step stale by EM construction) is not."""
    if k_reals is not None:
        k_reals = np.asarray(k_reals, np.int32)
        if np.any(k_reals < 1) or np.any(k_reals > k_real):
            raise ValueError(f"k_reals entries must be in [1, {k_real}], "
                             f"got {k_reals.tolist()}")
    data_shards, model_shards = mesh_shape(mesh)

    def fit(points, weights, shift, means0, var0, log_w0):
        R, k_pad, d = means0.shape
        k_local = k_pad // model_shards
        acc = points.dtype
        tiny = jnp.asarray(np.finfo(np.dtype(str(acc))).tiny, acc)
        pi_floor = jnp.maximum(jnp.asarray(1e-300, acc), tiny)
        # Per-member real mask (R, k_pad); homogeneous restarts broadcast
        # one row (identical arithmetic to the former (k_pad,) mask).
        if k_reals is not None and k_reals.shape != (R,):
            raise ValueError(f"k_reals must have shape ({R},), got "
                             f"{k_reals.shape}")
        ks = (np.full((R,), k_real, np.int32) if k_reals is None
              else k_reals)
        real = jnp.asarray(np.arange(k_pad)[None, :] < ks[:, None])
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        w_total = lax.psum(jnp.sum(weights.astype(acc)), DATA_AXIS)

        def estats_one(means_c, var, log_w):
            return _diag_estats_block(
                points, weights, shift, means_c, var, log_w,
                m_idx=m_idx, k_local=k_local, k_pad=k_pad,
                chunk_size=chunk_size, model_shards=model_shards,
                reg_covar=reg_covar, tiny=tiny, acc=acc,
                pipeline=pipeline)

        def body(state):
            it, means_c, var, log_w, prev, hist, done, n_it, conv = state
            st = jax.vmap(estats_one)(means_c, var, log_w)
            mu, new_var, new_log_w = _diag_m_step(
                st, w_total=w_total, reg_covar=reg_covar, tiny=tiny,
                pi_floor=pi_floor, real=real, cov_type=cov_type, acc=acc)
            ll = st.loglik / w_total                     # (R,)
            # Frozen restarts keep their parameters and recorded state.
            keep = done[:, None, None]
            means_c = jnp.where(keep, means_c,
                                jnp.where(real[:, :, None], mu,
                                          means_c))
            var = jnp.where(keep, var,
                            jnp.where(real[:, :, None], new_var, var))
            log_w = jnp.where(done[:, None], log_w, new_log_w)
            hist = hist.at[:, it].set(jnp.where(done, 0.0, ll))
            now_conv = jnp.abs(ll - prev) < tol
            n_it = jnp.where(done, n_it, it + 1)
            conv = jnp.where(done, conv, now_conv)
            prev = jnp.where(done, prev, ll)
            done = done | now_conv
            return (it + 1, means_c, var, log_w, prev, hist, done, n_it,
                    conv)

        def cond(state):
            it, *_, done, _, _ = state
            return (it < max_iter) & ~jnp.all(done)

        state = (jnp.int32(0), means0.astype(acc), var0.astype(acc),
                 log_w0.astype(acc),
                 jnp.full((R,), -jnp.inf, acc),
                 jnp.zeros((R, max_iter), acc),
                 jnp.zeros((R,), bool), jnp.zeros((R,), jnp.int32),
                 jnp.zeros((R,), bool))
        (_, means_c, var, log_w, prev, hist, done, n_it,
         conv) = lax.while_loop(cond, body, state)
        # prev holds each restart's LAST recorded lower bound; a
        # diverged restart's NaN is masked to -inf so it cannot win
        # (and NaN would otherwise poison argmax).
        final_lls = jnp.where(jnp.isfinite(prev), prev, -jnp.inf)
        if return_all:
            # Sweep mode: ONE extra vmapped E pass scores each member's
            # FINAL parameters (the fresh quantity BIC/AIC is defined
            # on), then every member's state goes back for host-side
            # criterion selection.
            st = jax.vmap(estats_one)(means_c, var, log_w)
            final_scores = jnp.where(jnp.isfinite(st.loglik),
                                     st.loglik / w_total, -jnp.inf)
            return (means_c, var, log_w, n_it, hist, conv, final_lls,
                    final_scores)
        best = jnp.argmax(final_lls)
        return (means_c[best], var[best], log_w[best], n_it[best],
                hist[best], conv[best], best, final_lls)

    out_specs = ((P(None, None, None), P(None, None, None), P(None, None),
                  P(None), P(None, None), P(None), P(None), P(None))
                 if return_all
                 else (P(None, None), P(None, None), P(None), P(),
                       P(None), P(), P(), P(None)))
    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(None, None, None), P(None, None, None),
                  P(None, None)),
        out_specs=out_specs,
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_gmm_fit_full_fn(mesh: Mesh, *, chunk_size: int, k_real: int,
                         max_iter: int, tol: float, reg_covar: float,
                         pipeline: int = 1):
    """FULL-covariance on-device EM loop: all iterations in ONE dispatch
    (the 'full' analogue of ``make_gmm_fit_fn``, r4 — the r4 host path
    initially shipped full/tied host-loop-only).

    Per iteration: batched precision Cholesky of the carried (k_pad, D,
    D) covariances (``_prec_chol_dev`` — jnp.linalg.cholesky +
    triangular solve, tiny against the E pass), the chunked full E pass,
    psum-embed, and the M-step in the accumulation dtype (scatter/R -
    mu mu^T + reg I, diagonal floored at tiny).  A component collapsing
    to a non-PD covariance yields NaN loglik -> the caller's loud
    non-finite error (the device loop cannot raise sklearn's pointed
    ill-defined-covariance message; the float64 host loop can).

    Returns ``fit(points, weights, shift, means0_c, cov0, log_w0,
    prev0) -> (means_c, cov, log_w, n_iter, ll_hist[max_iter],
    converged)``, everything replicated, tables (k_pad, ...) with
    padding components carried as ``log_w = -inf``.  ``prev0`` seeds
    the convergence baseline (``-inf`` fresh; segmented/resumed fits
    pass the last iteration's mean loglik — see ``make_gmm_fit_fn``).
    """
    data_shards, model_shards = mesh_shape(mesh)

    def fit(points, weights, shift, means0, cov0, log_w0, prev0):
        k_pad, d = means0.shape
        k_local = k_pad // model_shards
        acc = points.dtype
        tiny = jnp.asarray(np.finfo(np.dtype(str(acc))).tiny, acc)
        pi_floor = jnp.maximum(jnp.asarray(1e-300, acc), tiny)
        real = jnp.arange(k_pad) < k_real
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        w_total = lax.psum(jnp.sum(weights.astype(acc)), DATA_AXIS)
        diag_idx = jnp.arange(d)

        def estats(means_c, cov, log_w):
            p_chol, ldh = _prec_chol_dev(cov, tiny)
            # Padding components carry identity covariance (benign) and
            # -inf log_w, so their density never receives responsibility.
            off = jnp.asarray(m_idx * k_local, jnp.int32)
            blk = lambda a: lax.dynamic_slice(
                a, (off,) + (jnp.int32(0),) * (a.ndim - 1),
                (k_local,) + a.shape[1:])
            st = _scan_estats_full(
                points, weights, blk(means_c).astype(acc),
                blk(p_chol).astype(acc), blk(ldh).astype(acc),
                blk(log_w).astype(acc), shift, chunk_size=chunk_size,
                model_shards=model_shards, pipeline=pipeline)
            return _embed_psum_full(st, k_pad, k_local, model_shards)

        def body(state):
            it, means_c, cov, log_w, prev, hist, _, _ = state
            st = estats(means_c, cov, log_w)
            Rc = jnp.maximum(st.resp_sum, 10 * tiny)
            mu = st.xsum / Rc[:, None]
            new_cov = (st.scatter / Rc[:, None, None]
                       - mu[:, :, None] * mu[:, None, :])
            diag = new_cov[:, diag_idx, diag_idx]
            new_cov = new_cov.at[:, diag_idx, diag_idx].set(
                jnp.maximum(diag + reg_covar,
                            jnp.maximum(jnp.asarray(reg_covar, acc),
                                        tiny)))
            pi = jnp.maximum(st.resp_sum / jnp.maximum(w_total, pi_floor),
                             pi_floor)
            pi = pi / jnp.sum(jnp.where(real, pi, 0.0))
            new_log_w = jnp.where(real, jnp.log(pi), -jnp.inf)
            ll = st.loglik / w_total
            hist = hist.at[it].set(ll)
            conv = jnp.abs(ll - prev) < tol
            eye = jnp.broadcast_to(jnp.eye(d, dtype=acc), cov.shape)
            # All-finite flag (ISSUE 5) — see make_gmm_fit_fn: a non-PD
            # component's NaN loglik stops the loop at the diverging
            # iteration instead of spinning to max_iter.
            return (it + 1, jnp.where(real[:, None], mu, means_c),
                    jnp.where(real[:, None, None], new_cov, eye),
                    new_log_w, ll, hist, conv, jnp.isfinite(ll))

        def cond(state):
            it, *_, conv, ok = state
            return (it < max_iter) & ~conv & ok

        eye = jnp.broadcast_to(jnp.eye(d, dtype=acc), cov0.shape)
        cov_start = jnp.where(real[:, None, None], cov0.astype(acc), eye)
        state = (jnp.int32(0), means0.astype(acc), cov_start,
                 log_w0.astype(acc), jnp.asarray(prev0).astype(acc),
                 jnp.zeros((max_iter,), acc), jnp.asarray(False),
                 jnp.asarray(True))
        it, means_c, cov, log_w, _, hist, conv, _ = lax.while_loop(
            cond, body, state)
        return means_c, cov, log_w, it, hist, conv

    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(None, None), P(None, None, None), P(None), P()),
        out_specs=(P(None, None), P(None, None, None), P(None), P(),
                   P(), P()),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_gmm_fit_tied_fn(mesh: Mesh, *, chunk_size: int, k_real: int,
                         max_iter: int, tol: float, reg_covar: float,
                         pipeline: int = 1):
    """TIED-covariance on-device EM loop: the total scatter is computed
    ONCE inside the dispatch (loop-invariant), each iteration factors
    the single shared (D, D) covariance, transforms the means, runs the
    tied E pass, and M-steps via ``(T - sum_k R_k mu_k mu_k^T)/W``.

    Returns ``fit(points, weights, shift, means0_c, cov0, log_w0,
    prev0) -> (means_c, cov (D, D), log_w, n_iter, ll_hist,
    converged)``.  ``prev0`` seeds the convergence baseline (``-inf``
    fresh; segmented/resumed fits pass the last iteration's mean
    loglik — see ``make_gmm_fit_fn``)."""
    data_shards, model_shards = mesh_shape(mesh)

    def fit(points, weights, shift, means0, cov0, log_w0, prev0):
        k_pad, d = means0.shape
        k_local = k_pad // model_shards
        acc = points.dtype
        tiny = jnp.asarray(np.finfo(np.dtype(str(acc))).tiny, acc)
        pi_floor = jnp.maximum(jnp.asarray(1e-300, acc), tiny)
        real = jnp.arange(k_pad) < k_real
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        w_total = lax.psum(jnp.sum(weights.astype(acc)), DATA_AXIS)
        diag_idx = jnp.arange(d)

        # Loop-invariant total scatter (psum over data; identical on
        # every model replica).
        xc_all = points - shift[None, :]
        T = lax.psum(lax.dot_general(
            xc_all * weights.astype(acc)[:, None], xc_all,
            (((0,), (0,)), ((), ())), preferred_element_type=acc,
            precision=lax.Precision.HIGHEST), DATA_AXIS)

        def estats(means_c, cov, log_w):
            p_chol, ldh = _prec_chol_dev(cov, tiny)
            means_t = means_c @ p_chol
            off = jnp.asarray(m_idx * k_local, jnp.int32)
            blk = lambda a: lax.dynamic_slice(
                a, (off,) + (jnp.int32(0),) * (a.ndim - 1),
                (k_local,) + a.shape[1:])
            st = _scan_estats_tied(
                points, weights, blk(means_t).astype(acc),
                p_chol.astype(acc), ldh.astype(acc),
                blk(log_w).astype(acc), shift, chunk_size=chunk_size,
                model_shards=model_shards, pipeline=pipeline)
            return _embed_psum(st, k_pad, k_local, model_shards)

        def body(state):
            it, means_c, cov, log_w, prev, hist, _, _ = state
            st = estats(means_c, cov, log_w)
            Rc = jnp.maximum(st.resp_sum, 10 * tiny)
            mu = st.xsum / Rc[:, None]
            mu_real = jnp.where(real[:, None], mu, 0.0)
            new_cov = (T - jnp.einsum("k,kd,ke->de", st.resp_sum,
                                      mu_real, mu_real,
                                      precision=lax.Precision.HIGHEST)
                       ) / jnp.maximum(w_total, pi_floor)
            diag = new_cov[diag_idx, diag_idx]
            new_cov = new_cov.at[diag_idx, diag_idx].set(
                jnp.maximum(diag + reg_covar,
                            jnp.maximum(jnp.asarray(reg_covar, acc),
                                        tiny)))
            pi = jnp.maximum(st.resp_sum / jnp.maximum(w_total, pi_floor),
                             pi_floor)
            pi = pi / jnp.sum(jnp.where(real, pi, 0.0))
            new_log_w = jnp.where(real, jnp.log(pi), -jnp.inf)
            ll = st.loglik / w_total
            hist = hist.at[it].set(ll)
            conv = jnp.abs(ll - prev) < tol
            # All-finite flag (ISSUE 5) — see make_gmm_fit_fn.
            return (it + 1, jnp.where(real[:, None], mu, means_c),
                    new_cov, new_log_w, ll, hist, conv,
                    jnp.isfinite(ll))

        def cond(state):
            it, *_, conv, ok = state
            return (it < max_iter) & ~conv & ok

        state = (jnp.int32(0), means0.astype(acc), cov0.astype(acc),
                 log_w0.astype(acc), jnp.asarray(prev0).astype(acc),
                 jnp.zeros((max_iter,), acc), jnp.asarray(False),
                 jnp.asarray(True))
        it, means_c, cov, log_w, _, hist, conv, _ = lax.while_loop(
            cond, body, state)
        return means_c, cov, log_w, it, hist, conv

    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(None, None), P(None, None), P(None), P()),
        out_specs=(P(None, None), P(None, None), P(None), P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_gmm_predict_full_fn(mesh: Mesh, *, chunk_size: int) -> Callable:
    """Full-covariance posterior pass (same contract as
    ``make_gmm_predict_fn``)."""
    data_shards, model_shards = mesh_shape(mesh)

    def predict(points, shift, means, prec_chol, log_det_half,
                log_weights):
        k_local, d = means.shape
        return _predict_from_logp(
            lambda xc: _log_prob_full_chunk(
                xc - shift[None, :], means, prec_chol, log_det_half,
                log_weights),
            points, chunk_size, k_local, d, model_shards)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None), P(MODEL_AXIS, None),
                  P(MODEL_AXIS, None, None), P(MODEL_AXIS),
                  P(MODEL_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_gmm_predict_tied_fn(mesh: Mesh, *, chunk_size: int) -> Callable:
    """Tied-covariance posterior pass (same contract as
    ``make_gmm_predict_fn``; ``means_t`` pre-transformed)."""
    data_shards, model_shards = mesh_shape(mesh)

    def predict(points, shift, means_t, prec_chol, log_det_half,
                log_weights):
        k_local, d = means_t.shape
        return _predict_from_logp(
            lambda xc: _log_prob_tied_chunk(
                xc - shift[None, :], means_t, prec_chol, log_det_half,
                log_weights),
            points, chunk_size, k_local, d, model_shards)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None), P(MODEL_AXIS, None),
                  P(None, None), P(), P(MODEL_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


def _diag_estats_block(points, weights, shift, means_c, var, log_w, *,
                       m_idx, k_local, k_pad, chunk_size, model_shards,
                       reg_covar, tiny, acc, pipeline: int = 1):
    """ONE restart's diag/spherical E statistics inside a device loop:
    floor the covariance at max(reg_covar, tiny), slice this shard's
    model block, run the chunked scan, psum-embed.  Shared by the
    single-restart and the vmapped multi-restart loops so the
    hard-won floor/precision rules exist exactly once."""
    cv = jnp.maximum(var, jnp.maximum(jnp.asarray(reg_covar, acc), tiny))
    inv_var = 1.0 / cv
    log_det = jnp.sum(jnp.log(cv), axis=1)
    off = jnp.asarray(m_idx * k_local, jnp.int32)
    blk = lambda a: lax.dynamic_slice(
        a, (off,) + (jnp.int32(0),) * (a.ndim - 1),
        (k_local,) + a.shape[1:])
    st = _scan_estats(points, weights, blk(means_c).astype(acc),
                      blk(inv_var).astype(acc), blk(log_det).astype(acc),
                      blk(log_w).astype(acc), shift,
                      chunk_size=chunk_size, model_shards=model_shards,
                      pipeline=pipeline)
    return _embed_psum(st, k_pad, k_local, model_shards)


def _diag_m_step(st, *, w_total, reg_covar, tiny, pi_floor, real,
                 cov_type, acc):
    """The diag/spherical device M-step, axis-agnostic (works on plain
    (k_pad, ...) statistics and on restart-batched (R, k_pad, ...)
    ones): mean, tiny-floored variance (spherical averages over D),
    normalized mixing weights.  Returns (mu, new_var, new_log_w)."""
    Rc = jnp.maximum(st.resp_sum, 10 * tiny)
    mu = st.xsum / Rc[..., None]
    new_var = jnp.maximum(
        st.x2sum / Rc[..., None] - mu ** 2 + reg_covar,
        jnp.maximum(jnp.asarray(reg_covar, acc), tiny))
    if cov_type == "spherical":
        new_var = jnp.broadcast_to(
            jnp.mean(new_var, axis=-1, keepdims=True), new_var.shape)
    pi = jnp.maximum(st.resp_sum / jnp.maximum(w_total, pi_floor),
                     pi_floor)
    pi = pi / jnp.sum(jnp.where(real, pi, 0.0), axis=-1, keepdims=True)
    new_log_w = jnp.where(real, jnp.log(pi), -jnp.inf)
    return mu, new_var, new_log_w


@_obs_trace.traced_builder
def make_gmm_fit_fn(mesh: Mesh, *, chunk_size: int, k_real: int,
                    max_iter: int, tol: float, reg_covar: float,
                    cov_type: str = "diag", pipeline: int = 1):
    """Build the FULLY ON-DEVICE EM loop: all iterations in ONE dispatch
    under ``lax.while_loop`` — the mixture analogue of
    ``distributed.make_fit_fn`` (r2 VERDICT next-round #3).

    Per iteration: slice this shard's component block from the carried
    full tables, run the chunked E pass, psum-embed, M-step IN THE
    ACCUMULATION DTYPE on device (the host loop M-steps in float64 — the
    same documented division divergence as the K-Means device loop), and
    test ``|mean loglik - prev| < tol`` (sklearn semantics, matching the
    host loop).  Floors mirror the host M-step: ``R`` floored at
    ``10 * tiny``, mixing weights at ``max(1e-300, tiny(acc))`` — for
    float64 these equal the host constants exactly.

    Returns ``fit(points, weights, shift, means0_c, var0, log_w0,
    prev0) -> (means_c, var, log_w, n_iter, ll_hist[max_iter],
    converged)`` with everything replicated; ``means0_c``/``means_c``
    are in the centered frame (caller adds ``shift`` back), tables are
    (k_pad, ...) with padding components carried as ``log_w = -inf``.
    ``prev0`` seeds the convergence baseline (the previous iteration's
    mean log-likelihood): ``-inf`` for a fresh fit; a SEGMENTED or
    resumed fit passes the last completed iteration's value so the
    ``|ll - prev| < tol`` test is identical to an uninterrupted loop
    crossing that boundary (ISSUE 4 — bit-exact checkpoint parity).
    """
    data_shards, model_shards = mesh_shape(mesh)

    def fit(points, weights, shift, means0, var0, log_w0, prev0):
        k_pad, d = means0.shape
        k_local = k_pad // model_shards
        acc = points.dtype
        tiny = jnp.asarray(np.finfo(np.dtype(str(acc))).tiny, acc)
        pi_floor = jnp.maximum(jnp.asarray(1e-300, acc), tiny)
        real = jnp.arange(k_pad) < k_real
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        w_total = lax.psum(jnp.sum(weights.astype(acc)), DATA_AXIS)

        def estats(means_c, var, log_w):
            # Floor at tiny(acc) even when reg_covar=0 (allowed by
            # validation): a collapsed component would otherwise give
            # inv_var=inf / log_det=-inf -> NaN loglik (r3 ADVICE; the
            # host paths floor at the same dtype-tiny in _params_dev).
            return _diag_estats_block(
                points, weights, shift, means_c, var, log_w,
                m_idx=m_idx, k_local=k_local, k_pad=k_pad,
                chunk_size=chunk_size, model_shards=model_shards,
                reg_covar=reg_covar, tiny=tiny, acc=acc,
                pipeline=pipeline)

        def body(state):
            it, means_c, var, log_w, prev, hist, _, _ = state
            st = estats(means_c, var, log_w)
            # The CARRIED/returned variance is floored at tiny too — a
            # var of exactly 0 would make the fitted model's precisions_
            # inf and its score()/predict() NaN even though the in-loop
            # E-step floors its own copy (review r4).  Spherical carries
            # its scalar variance broadcast over D so the diag E-step is
            # reused unchanged.
            mu, new_var, new_log_w = _diag_m_step(
                st, w_total=w_total, reg_covar=reg_covar, tiny=tiny,
                pi_floor=pi_floor, real=real, cov_type=cov_type, acc=acc)
            ll = st.loglik / w_total
            hist = hist.at[it].set(ll)
            conv = jnp.abs(ll - prev) < tol
            # All-finite flag (ISSUE 5): a non-finite log-likelihood
            # stops the loop at the DIVERGING iteration (|NaN - prev| <
            # tol is False, so without the flag the loop would spin NaNs
            # to max_iter); healthy trajectories are untouched.
            return (it + 1, jnp.where(real[:, None], mu, means_c),
                    jnp.where(real[:, None], new_var, var), new_log_w,
                    ll, hist, conv, jnp.isfinite(ll))

        def cond(state):
            it, *_, conv, ok = state
            return (it < max_iter) & ~conv & ok

        state = (jnp.int32(0), means0.astype(acc), var0.astype(acc),
                 log_w0.astype(acc), jnp.asarray(prev0).astype(acc),
                 jnp.zeros((max_iter,), acc), jnp.asarray(False),
                 jnp.asarray(True))
        it, means_c, var, log_w, _, hist, conv, _ = lax.while_loop(
            cond, body, state)
        return means_c, var, log_w, it, hist, conv

    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None),
                  P(None, None), P(None, None), P(None), P()),
        out_specs=(P(None, None), P(None, None), P(None), P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)
