"""The SPMD EM step for diagonal-covariance Gaussian mixtures.

Same execution model as the K-Means step (``distributed.make_step_fn``):
points sharded on the ``data`` mesh axis, parameters replicated, one
jitted ``shard_map`` whose only collective is a ``psum`` of dense
per-component accumulators.  The reference framework has no mixture
model at all — this is a beyond-reference family built on the same
TPU-first machinery (SURVEY.md §2.3 backend mapping).

TPU formulation of the E-step: for diagonal Gaussians,

    log N(x | mu_k, sigma_k^2)
      = -0.5 * [ sum_d x_d^2 * a_kd  -  2 sum_d x_d * (mu_kd * a_kd)
                 + sum_d mu_kd^2 * a_kd + sum_d log sigma_kd^2
                 + D * log 2pi ]                    with a = 1/sigma^2,

so the (chunk, k) log-density tile is TWO matmuls — ``x^2 @ a.T`` and
``x @ (mu*a).T`` — plus per-component row constants: the same
MXU-dominant shape as the K-Means distance pass.  Responsibilities come
from a max-subtracted softmax over k; the per-chunk accumulators

    R_k    = sum_i w_i r_ik                       (k,)
    S1_k   = sum_i w_i r_ik x_i                   (k, D)  [resp.T @ x]
    S2_k   = sum_i w_i r_ik x_i^2                 (k, D)  [resp.T @ x^2]
    ll     = sum_i w_i logsumexp_k(...)           ()

are all dense and psum-able; the M-step (host or caller side) is then
pi = R/W, mu = S1/R, sigma^2 = S2/R - mu^2 + reg.  Zero-weight padding
rows contribute nothing to any statistic.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kmeans_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, mesh_shape

_LOG2PI = math.log(2.0 * math.pi)


class EStats(NamedTuple):
    """Globally-reduced E-step statistics (everything psum-able)."""

    resp_sum: jax.Array    # (k,)   sum of weighted responsibilities
    xsum: jax.Array        # (k, D) responsibility-weighted point sums
    x2sum: jax.Array       # (k, D) responsibility-weighted square sums
    loglik: jax.Array      # ()     weighted total log-likelihood


def _log_prob_chunk(x, means, inv_var, log_det, log_weights):
    """(chunk, k) weighted log joint: log pi_k + log N(x | mu_k, s2_k)."""
    a = inv_var                                    # (k, D)
    b = means * inv_var                            # (k, D)
    x2a = lax.dot_general(x * x, a, (((1,), (1,)), ((), ())),
                          preferred_element_type=x.dtype)   # (c, k) MXU
    xb = lax.dot_general(x, b, (((1,), (1,)), ((), ())),
                         preferred_element_type=x.dtype)    # (c, k) MXU
    quad = x2a - 2.0 * xb + jnp.sum(means * b, axis=1)[None, :]
    d = x.shape[1]
    return (log_weights[None, :]
            - 0.5 * (quad + log_det[None, :] + d * _LOG2PI))


def estep_chunk(x, w, means, inv_var, log_det, log_weights):
    """One chunk's contribution to EStats (shared by step fn and tests)."""
    logp = _log_prob_chunk(x, means, inv_var, log_det, log_weights)
    m = jnp.max(logp, axis=1, keepdims=True)
    p = jnp.exp(logp - m)
    denom = jnp.sum(p, axis=1, keepdims=True)
    lse = (m[:, 0] + jnp.log(denom[:, 0]))
    resp = p / denom * w[:, None]                  # weighted, padded -> 0
    return EStats(
        resp_sum=jnp.sum(resp, axis=0),
        xsum=lax.dot_general(resp, x, (((0,), (0,)), ((), ())),
                             preferred_element_type=x.dtype),
        x2sum=lax.dot_general(resp, x * x, (((0,), (0,)), ((), ())),
                              preferred_element_type=x.dtype),
        loglik=jnp.sum(jnp.where(w > 0, lse * w, 0.0)),
    )


def make_gmm_step_fn(mesh: Mesh, *, chunk_size: int) -> Callable:
    """Build the jitted SPMD E-step:
    (points, weights, means, inv_var, log_det, log_weights) -> EStats,
    fully replicated.  Parameters are replicated (no model-axis sharding
    for the mixture family — k*2D parameter tables are small next to the
    data); the data axis carries N exactly like the K-Means step."""
    data_shards, model_shards = mesh_shape(mesh)
    if model_shards > 1:
        raise ValueError(
            "GaussianMixture does not shard its parameter tables; build "
            "the mesh with model_shards=1 (the data axis still scales N)")

    def step(points, weights, means, inv_var, log_det, log_weights):
        k, d = means.shape
        acc = points.dtype
        n_chunks = points.shape[0] // chunk_size
        xs = (points.reshape(n_chunks, chunk_size, d),
              weights.astype(acc).reshape(n_chunks, chunk_size))

        def body(carry, chunk):
            xc, wc = chunk
            st = estep_chunk(xc, wc, means, inv_var, log_det, log_weights)
            return EStats(carry.resp_sum + st.resp_sum,
                          carry.xsum + st.xsum,
                          carry.x2sum + st.x2sum,
                          carry.loglik + st.loglik), None

        init = EStats(jnp.zeros((k,), acc), jnp.zeros((k, d), acc),
                      jnp.zeros((k, d), acc), jnp.zeros((), acc))
        st, _ = lax.scan(body, init, xs)
        return EStats(*(lax.psum(s, DATA_AXIS) for s in st))

    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None, None),
                  P(None, None), P(None), P(None)),
        out_specs=EStats(P(None), P(None, None), P(None, None), P()),
        check_vma=False)
    return jax.jit(mapped)


def make_gmm_predict_fn(mesh: Mesh, *, chunk_size: int) -> Callable:
    """Jitted sharded posterior pass:
    (points, means, inv_var, log_det, log_weights) ->
    (labels, log_resp (n, k), log_prob (n,)) — the marginal
    ``log p(x) = logsumexp_k`` rides along for score/score_samples."""
    data_shards, model_shards = mesh_shape(mesh)

    def predict(points, means, inv_var, log_det, log_weights):
        k, d = means.shape
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)

        def body(_, xc):
            logp = _log_prob_chunk(xc, means, inv_var, log_det,
                                   log_weights)
            lse = jax.scipy.special.logsumexp(logp, axis=1, keepdims=True)
            return None, (jnp.argmax(logp, axis=1).astype(jnp.int32),
                          logp - lse, lse[:, 0])

        _, (labels, logr, lse) = lax.scan(body, None, xs)
        return labels.reshape(-1), logr.reshape(-1, k), lse.reshape(-1)

    mapped = jax.shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None, None), P(None, None),
                  P(None), P(None)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS, None), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)
