"""Multi-host (multi-process) setup helpers.

The reference delegates multi-node execution to the Spark driver/executor
runtime (outside its repo; SURVEY.md §2.3).  Here multi-host is the same
SPMD program: every host runs the identical jitted step over the global
``Mesh``; XLA routes the ``psum``/``all_gather`` over ICI within a slice
and DCN across slices.  Because every statistic the host loop consumes
(sums, counts, SSE) is REPLICATED by the psum, each host's driver loop
computes the identical centroid update and convergence decision — no
cross-host coordination code is needed beyond this initialization.

Typical multi-host entry:

    from kmeans_tpu.parallel.multihost import initialize
    initialize()                       # jax.distributed handshake
    mesh = make_mesh()                 # global devices, all hosts
    km = KMeans(k=1024, mesh=mesh)
    km.fit(X_local_shard_or_full)      # same code as single host

Data loading: each host may pass the full array (simplest; placement
shards it) or use `jax.make_array_from_process_local_data` for
host-sharded loading of very large datasets.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper; no-op if already initialized
    or running single-process (so the same script runs everywhere)."""
    if jax.process_count() > 1:
        return                          # already initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except (ValueError, RuntimeError):
        # Single-process run (no coordinator env) — nothing to do.
        pass


def is_primary() -> bool:
    """True on the process that should own logging/artifact writes."""
    return jax.process_index() == 0
