"""Multi-host (multi-process) setup helpers.

The reference delegates multi-node execution to the Spark driver/executor
runtime (outside its repo; SURVEY.md §2.3).  Here multi-host is the same
SPMD program: every host runs the identical jitted step over the global
``Mesh``; XLA routes the ``psum``/``all_gather`` over ICI within a slice
and DCN across slices.  Because every statistic the host loop consumes
(sums, counts, SSE) is REPLICATED by the psum, each host's driver loop
computes the identical centroid update and convergence decision — no
cross-host coordination code is needed beyond this initialization.

Typical multi-host entry:

    from kmeans_tpu.parallel.multihost import initialize
    initialize()                       # jax.distributed handshake
    mesh = make_mesh()                 # global devices, all hosts
    km = KMeans(k=1024, mesh=mesh)
    km.fit(X_local_shard_or_full)      # same code as single host

Data loading: each host may pass the full array (simplest; placement
shards it) or use `jax.make_array_from_process_local_data` for
host-sharded loading of very large datasets.
"""

from __future__ import annotations

from typing import Optional

import jax

from kmeans_tpu.obs import trace as _obs_trace


_CLUSTER_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
    "CLOUD_TPU_TASK_ID", "TPU_WORKER_ID",
)


def _cluster_env_present() -> bool:
    """True when the environment indicates this process belongs to a
    multi-process cluster job (jax.distributed auto-detection sources)."""
    import os
    return any(os.environ.get(v) for v in _CLUSTER_ENV_VARS)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper; no-op if already initialized
    or running single-process (so the same script runs everywhere)."""
    # NOTE: probe via jax.distributed.is_initialized(), NOT
    # jax.process_count() — the latter initializes the XLA backends, which
    # would make the distributed handshake below impossible.  Older JAX
    # (< 0.6) has no is_initialized(); its documented equivalent is the
    # distributed global_state client probe.
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        if probe():
            return                      # already initialized
    else:
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is not None:
            return                      # already initialized
    explicit = any(a is not None for a in
                   (coordinator_address, num_processes, process_id))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except (RuntimeError, ValueError):
        # RuntimeError: "must be called before any JAX computations" —
        # backends already initialized.  ValueError: no coordinator could
        # be auto-detected (ADVICE r1).  Either way, if the caller passed
        # explicit coordinates or the environment says this is one process
        # of a cluster job, swallowing would silently downgrade EVERY host
        # to a wrong single-process fit — raise.  Otherwise this is a
        # plain single-process program calling initialize() late/without a
        # coordinator, which is harmless.
        if explicit or _cluster_env_present():
            raise


def is_primary() -> bool:
    """True on the process that should own logging/artifact writes."""
    return jax.process_index() == 0


def simulated_world_env(process_index: int, process_count: int,
                        host: Optional[str] = None) -> dict:
    """Environment overrides that make a PLAIN subprocess a member of a
    simulated fleet (ISSUE 19): the ``KMEANS_TPU_PROCESS_INDEX`` /
    ``_COUNT`` / ``_HOST`` identity variables ``obs.identity`` resolves
    before any jax probe, so per-process heartbeat/trace sinks suffix
    correctly and host-targeted fault injection
    (``faults.inject_host_kill``) can pick its victim — WITHOUT a
    ``jax.distributed`` handshake.  This is the mode the autopilot's
    launcher uses on a single machine (and in CI, where the CPU backend
    has no cross-process collectives); on a real cluster the launcher
    passes coordinator env instead and the same identity layer reads
    ``jax.process_index()``."""
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} outside world of "
            f"{process_count}")
    return {
        "KMEANS_TPU_PROCESS_INDEX": str(process_index),
        "KMEANS_TPU_PROCESS_COUNT": str(process_count),
        "KMEANS_TPU_HOST": host or f"sim{process_index}",
    }


def fleet_barrier(tag: str = "fit-start") -> None:
    """Telemetry clock anchor (ISSUE 13): a cross-host barrier + a
    ``fleet.barrier`` trace event, emitted by the fit preludes.

    The fleet merge (``obs.fleet.merge_traces``) aligns per-host
    monotonic clocks on these events: all hosts exit the barrier at the
    same true instant up to the release skew, so the k-th barrier on
    host A pairs with the k-th on host B.  Contract:

    * **obs=0 true no-op** — with no tracer installed this returns
      after one ``None`` check: no barrier, no collective, no record.
      Corollary: telemetry scopes must be installed FLEET-WIDE (every
      host or none) — a barrier some hosts skip would deadlock the
      rest (documented in docs/OBSERVABILITY.md "Fleet").
    * Multi-process: the barrier is one tiny ``process_allgather`` (the
      same primitive ``from_process_local`` already pays per dataset),
      safe to repeat; the event stamps ``synced=True`` and the merge
      trusts it as a clock anchor.
    * Single-process (or a simulated fleet of plain processes): no
      collective exists to sync on — the event is still emitted with
      ``synced=False``, a sequence marker only; the merge then falls
      back to wall-clock alignment.
    """
    if _obs_trace.get_tracer() is None:
        return
    synced = False
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        import numpy as np
        with _obs_trace.span("collective", op="process_allgather",
                             site=f"fleet_barrier:{tag}"):
            multihost_utils.process_allgather(
                np.asarray([jax.process_index()], dtype=np.int32))
        synced = True
    _obs_trace.event("fleet.barrier", tag=tag, synced=synced)
