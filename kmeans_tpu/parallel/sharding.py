"""Host->mesh data placement: padding, sharding, and global sampling.

Replaces the reference's data-distribution story: ``sc.parallelize`` +
``repartition`` + ``cache`` (kmeans_spark.py:369/418/568, README.md:71).
Points go on device ONCE, sharded along the data axis, and stay resident for
the whole fit (the moral equivalent of ``rdd.cache()``, kmeans_spark.py:256 —
except there is nothing to "unpersist": the array's lifetime is its Python
lifetime).

Padding: shard and chunk sizes must be static under jit, so N is padded up to
``data_shards * chunk`` rows with a 0/1 weight mask; padded rows are inert in
every statistic (see ops.assign.assign_reduce).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.obs import metrics_registry as _obs_metrics
from kmeans_tpu.obs import trace as _obs_trace
from kmeans_tpu.parallel.mesh import DATA_AXIS, mesh_shape


@functools.partial(jax.jit, static_argnames=("m",))
def _gumbel_rows(points, weights, seed, m: int):
    """Draw ``m`` distinct positive-weight rows, uniformly, fully on
    device: ONE seeded Gumbel top-k over the masked rows.  Gumbel-top-k
    IS sequential Gumbel-argmax-with-remasking in distribution (uniform
    without replacement over positive-weight rows), but costs one O(n)
    ``top_k`` instead of m sequential argmax+scatter rounds — the r5
    time-to-solution run measured the sequential loop at 4.7 s for
    k=1024 over 10M rows, dominating a warm fit's wall time; the
    one-shot draw is ~0.25 s.  (Draw IDENTITIES change vs the r1-r4
    loop — still deterministic per seed, and the distribution is the
    same.)  GSPMD-parallel over sharded inputs (the top_k and the row
    gather lower to cross-shard collectives), so it works on multi-host
    process-local datasets where no host can index the global row space
    — the capability gap behind r1 VERDICT #6."""
    n, _ = points.shape
    g = jax.random.gumbel(jax.random.PRNGKey(seed), (n,), jnp.float32)
    score = jnp.where(weights > 0, g, -jnp.inf)
    _, idx = jax.lax.top_k(score, m)
    return points[idx]


#: Below this many (n_local * k) elements the whole local shard runs as
#: ONE chunk — no scan at all.  Measured on TPU v5e (experiments/
#: exp_small_shapes.py, r5): at blobs1m (1M x 16, k=64) the single-chunk
#: pass is 1.72x faster than the 2^17-capped scan (0.337 vs 0.580
#: ms/iter), and the shapes that already ran single-chunk (100k x 10 k=5,
#: 60k x 784 k=10) beat every chunked variant.  2^26 f32 elements is a
#: 256 MB distance matrix — trivially resident on a 16 GB chip; batched
#: n_init multiplies the temporaries by R (a vmapped (R, n, k) matmul),
#: still < 3 GB at R=10 in this region.  Set ``chunk_size`` explicitly
#: to override (e.g. extreme R on a memory-constrained chip).
SINGLE_CHUNK_ELEMS = 1 << 26


def choose_chunk_size(n_local: int, k: int, d: int,
                      budget_elems: Optional[int] = None,
                      max_chunk: Optional[int] = None) -> int:
    """Pick the scan chunk size for the fused assign+reduce pass.

    Two measured regimes (experiments/exp_small_shapes.py has the r5
    sweep; the r3 plateau measurement is below):

    * ``n_local * k <= SINGLE_CHUNK_ELEMS`` at the DEFAULT budget:
      return one whole-shard chunk — the scan exists only to bound live
      (chunk, k) HBM temporaries, and in this region the unbounded
      temporary is small enough that eliding the loop wins outright
      (1.72x at 1M x 16 k=64).  The chunk is ``n_local`` rounded UP to
      the f32 sublane multiple, so the padded shard is exactly one
      chunk.  Callers passing an explicit ``budget_elems`` (the EM
      paths: ``models.gmm.EM_CHUNK_BUDGET``) opt OUT of the shortcut —
      EM measured the opposite direction (smaller tiles beat larger
      ones 2x at 2M x 128 k=256, models/gmm.py), so the K-Means
      single-chunk result must not be extrapolated onto it.

    * Otherwise, scan: measured on TPU v5e (N=2M, D=128, k=1024),
      per-pass cost falls monotonically from 14.6 ms at chunk=2048 to a
      ~10.6 ms plateau at chunk=32768..131072, then degrades again at
      >=512k — larger chunks amortize scan/loop overhead while XLA
      tiles the (chunk, k) distance matrix internally regardless of the
      scan granularity.  The default budget of 2^25 tile elements puts
      k=1024 at the 32768-chunk plateau; ``max_chunk`` caps low-k
      configs so the scan still bounds live HBM temporaries.  Rounded
      to a multiple of 8 (f32 sublane), at least 128 (lane width), so
      tiles map cleanly onto the TPU's (8, 128) layout.
    """
    if budget_elems is None:
        if n_local * max(k, 1) <= SINGLE_CHUNK_ELEMS:
            one = int(max(128, -(-n_local // 8) * 8))
            if max_chunk is not None:
                # A caller passing an EXPLICIT cap (None = unspecified,
                # so even an explicit 2^17 counts) keeps it in the
                # single-chunk region — the shortcut deliberately
                # exceeds the implicit default cap (that is its whole
                # point), but it must not silently violate a stated
                # contract (ADVICE r5 low).
                one = min(one, int(max(128, (max_chunk // 8) * 8)))
            return one
        budget_elems = 1 << 25
    if max_chunk is None:
        max_chunk = 1 << 17
    chunk = max(128, min(n_local, budget_elems // max(k, 1), max_chunk))
    chunk = min(chunk, max(n_local, 128))
    return int(max(8, (chunk // 8) * 8))


def clamp_chunk_for_k(chunk: int, k: int,
                      budget_elems: int = SINGLE_CHUNK_ELEMS,
                      max_chunk: Optional[int] = None) -> int:
    """Bound the (chunk, k) fit-time temporary when the REAL k exceeds
    the ``k_hint`` a dataset's chunk was auto-chosen with (r5 review
    finding): a ``from_npy(..., k_hint=16)`` load of a 4M-row shard gets
    a whole-shard single chunk under the SINGLE_CHUNK_ELEMS shortcut,
    and a later ``KMeans(k=1024).fit(ds)`` would materialize a
    (4M, 1024) distance tile (~16 GB) — the old 2^17 row cap bounded
    that mismatch; this clamp restores the bound using the fitted k.

    Returns the largest multiple-of-8 DIVISOR of ``chunk`` whose
    (chunk', k) tile fits ``budget_elems`` — a divisor, because the
    dataset's padding committed to whole-``chunk`` multiples per shard
    (shard_points), so only divisors re-chunk without re-padding.
    ``max_chunk`` (optional) additionally bounds the clamped divisor by
    a scan-regime row cap — EM callers pass their measured plateau
    (``models.gmm.EM_MAX_CHUNK``) so mis-hinted foreign datasets land
    near it instead of wherever the element budget alone allows
    (ADVICE r5 low).

    No-op when the tile already fits (every auto-chosen chunk whose
    hint matched the fitted k); when ``chunk`` is already at or below
    the 128-row floor ``choose_chunk_size`` enforces — clamping below
    it would re-shrink chunks the auto rule DELIBERATELY floored (a
    k=1024 full-covariance GMM on D=1024 data floors at 128; clamping
    to the pure budget would scan 8-row tiles, r5 review); and when
    ``chunk`` is not a multiple of 8 — an explicit user ``chunk_size``
    outside the auto rule's 8-row grid must pass through untouched,
    because only true divisors of the committed chunk re-chunk safely
    and ``chunk // 8`` would silently floor it.

    Divisor-pathology fallback (ADVICE r5 medium): when the committed
    chunk has no multiple-of-8 divisor that is both >= 128 and within
    the budget (sparse divisor structure — e.g. a 4,000,008-row
    single-chunk shard, whose divisors jump from 24 straight to
    1,333,336), the budget-honoring answer would scan degenerate
    sub-sublane tiles (~167k 24-row scan steps for that shard at
    k=1024).  Instead the SMALLEST multiple-of-8 divisor >= 128 is
    returned — accepting the budget overshoot — with a ``UserWarning``
    naming the pathology and the fix (reshard, or load with the real
    ``k_hint``/an explicit ``chunk_size``)."""
    fits = chunk * max(k, 1) <= budget_elems and \
        (max_chunk is None or chunk <= max_chunk)
    if fits or chunk <= 128 or chunk % 8:
        return chunk
    target = max(8, budget_elems // max(k, 1))
    if max_chunk is not None:
        target = min(target, max(8, max_chunk))
    base = chunk // 8
    best = 1          # largest divisor*8 within target
    small = base      # smallest divisor*8 that is >= 128
    i = 1
    while i * i <= base:
        if base % i == 0:
            for cand in (i, base // i):
                if cand * 8 <= target and cand > best:
                    best = cand
                if cand * 8 >= 128 and cand < small:
                    small = cand
        i += 1
    if best * 8 >= 128:
        return best * 8
    import warnings
    warnings.warn(
        f"clamp_chunk_for_k: the committed chunk {chunk} has no "
        f"multiple-of-8 divisor between 128 and the {target}-row "
        f"budget for k={k}; using {small * 8} rows (budget overshoot) "
        f"instead of degenerate {best * 8}-row scan tiles — reshard the "
        f"dataset or load it with the real k_hint / an explicit "
        f"chunk_size to avoid the oversized tile", UserWarning,
        stacklevel=3)
    return small * 8


#: choose_chunk_size's hard floor — the smallest chunk the auto rule
#: ever emits (one TPU lane-width of rows).  ``backoff_chunk`` will not
#: shrink below it: past this point the scan tiles are degenerate and a
#: genuine OOM needs a different remedy (smaller k, more chips).
MIN_CHUNK = 128


def backoff_chunk(chunk: int, floor: int = MIN_CHUNK) -> Optional[int]:
    """The next-smaller chunk for OOM-graceful degradation (ISSUE 5):
    the LARGEST divisor of ``chunk`` that is ``<= chunk // 2`` and
    ``>= floor`` — a divisor, because the dataset's padding committed to
    whole-``chunk`` multiples per shard (``shard_points``), so only
    divisors re-chunk the already-placed array without re-padding
    (the same rule as ``clamp_chunk_for_k``).  Multiple-of-8 divisors
    (the f32 sublane grid every auto-chosen chunk lives on) are
    preferred; off-grid divisors are accepted only when no on-grid one
    exists (explicit user chunks).  Returns ``None`` when no further
    backoff is possible (``chunk`` already at or below the floor, or no
    divisor in range) — the caller then re-raises the original OOM."""
    if chunk <= floor:
        return None
    best_grid = best_any = None
    i = 1
    while i * i <= chunk:
        if chunk % i == 0:
            for cand in (i, chunk // i):
                if floor <= cand <= chunk // 2:
                    if cand % 8 == 0 and (best_grid is None
                                          or cand > best_grid):
                        best_grid = cand
                    if best_any is None or cand > best_any:
                        best_any = cand
        i += 1
    return best_grid if best_grid is not None else best_any


#: The committed fit-shape bucket ladder (ISSUE 15b): row-count
#: boundaries at {1, 1.25, 1.5, 1.75} x 2^e (floored at BUCKET_FLOOR
#: rows).  Serving's batch-bucket discipline applied to training: a
#: fit with ``bucket='auto'`` pads its staged shard (with the existing
#: inert zero-weight sentinel rows) up to the next boundary, so nearby
#: dataset sizes commit to ONE padded shape + chunk and therefore ONE
#: compiled program — a standing fleet accepts a new fit like the
#: serving engine accepts a request.  Quarter-power-of-two rungs bound
#: the padding waste at 25% worst-case (~11% expected under a
#: log-uniform size distribution).
BUCKET_RUNGS = (1.0, 1.25, 1.5, 1.75)
BUCKET_FLOOR = 256


def bucket_rows(n: int) -> int:
    """The smallest committed bucket boundary >= ``n`` (ISSUE 15b)."""
    n = int(n)
    if n <= BUCKET_FLOOR:
        return BUCKET_FLOOR
    e = int(np.floor(np.log2(n / BUCKET_FLOOR)))
    # Float log may land one exponent high/low at exact boundaries;
    # scan the neighborhood — correctness over cleverness.
    for ee in (e - 1, e, e + 1):
        for r in BUCKET_RUNGS:
            b = int(round(BUCKET_FLOOR * r * (2 ** ee)))
            if b >= n:
                return b
    return int(round(BUCKET_FLOOR * (2 ** (e + 2))))  # pragma: no cover


#: Candidate-set bucket floor (ISSUE 16): the two-level tier's member
#: lists are (C, L) tables whose width L is the largest per-cell member
#: count — bucketing L on the same quarter-power-of-two rungs as the
#: row ladder (with a lane-width floor, not the row floor: candidate
#: lists are k/C-ish, far below 256 at moderate k) means member-list
#: rebuilds across iterations and across cells commit to a handful of
#: compiled programs instead of one per distinct L.
CANDIDATE_FLOOR = 32


def bucket_candidates(n: int) -> int:
    """The smallest candidate-width bucket boundary >= ``n`` (ISSUE 16):
    ``bucket_rows`` rungs with the ``CANDIDATE_FLOOR`` floor."""
    n = int(n)
    if n <= CANDIDATE_FLOOR:
        return CANDIDATE_FLOOR
    e = int(np.floor(np.log2(n / CANDIDATE_FLOOR)))
    for ee in (e - 1, e, e + 1):
        for r in BUCKET_RUNGS:
            b = int(round(CANDIDATE_FLOOR * r * (2 ** ee)))
            if b >= n:
                return b
    return int(round(CANDIDATE_FLOOR * (2 ** (e + 2))))  # pragma: no cover


def check_bucket(bucket):
    """Validate (and normalize) the ``bucket`` knob grammar shared by
    every family and the CLI: ``'auto'`` | an int >= 0 (0 = exact
    shape, the bit-parity oracle).  ONE definition, so the families
    can never diverge on the grammar (review finding)."""
    if isinstance(bucket, str):
        if bucket != "auto":
            raise ValueError(f"bucket must be 'auto' or an int >= 0, "
                             f"got {bucket!r}")
        return bucket
    if int(bucket) < 0 or int(bucket) != bucket:
        raise ValueError(f"bucket must be 'auto' or an int >= 0, "
                         f"got {bucket!r}")
    return int(bucket)


def bucket_target(bucket, n: int) -> int:
    """Padded-row target for a validated ``bucket`` knob: the real row
    count at 0, the committed ladder boundary at ``'auto'``, the next
    multiple of an explicit int step — the ONE policy both model
    families' ``_bucket_target`` delegates to."""
    if bucket == "auto":
        return bucket_rows(n)
    if bucket:
        return -(-int(n) // bucket) * bucket
    return int(n)


def pad_points(x: np.ndarray, multiple: int,
               min_rows: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows of (n, D) to a multiple; return (padded, 0/1 weights).

    ``min_rows`` (ISSUE 15b) raises the padding target first — the
    shape-bucket mechanism: rows pad to the bucket boundary, THEN to
    the shard/chunk multiple; the extra rows carry weight 0 exactly
    like ordinary shard padding (inert in every statistic)."""
    n = x.shape[0]
    target = max(n, int(min_rows))
    pad = target - n + ((-target) % multiple)
    w = np.ones(n + pad, dtype=x.dtype)
    if pad:
        x = np.concatenate([x, np.zeros((pad, x.shape[1]), dtype=x.dtype)])
        w[n:] = 0.0
    return x, w


#: The ingest-mode knob grammar (ISSUE 18): how host rows become
#: mesh-sharded device arrays.  ``'mono'`` is the pinned parity oracle —
#: one blocking per-shard assembly (``make_array_from_callback``, shard
#: slices read as views, only the final shard's tail freshly padded);
#: ``'slab'`` is the staged path — shards grouped into HBM-planner-sized
#: slabs, uploaded double-buffered via
#: ``make_array_from_single_device_arrays`` so slab i+1's host->device
#: copy overlaps slab i's transfer completion.  The assembled array is
#: byte-identical either way (pinned, tests/test_ingest.py), so the
#: choice is purely a cost call; ``'auto'`` applies the committed
#: BENCH_INGEST decision rule (see :func:`resolve_ingest`).
INGEST_MODES = ("auto", "mono", "slab")


def check_ingest(ingest) -> str:
    """Validate the ``ingest`` knob grammar shared by the loaders and
    every family constructor: ``'auto' | 'mono' | 'slab'`` — ONE
    definition, the ``check_bucket`` convention."""
    if ingest not in INGEST_MODES:
        raise ValueError(f"ingest must be one of {INGEST_MODES}, "
                         f"got {ingest!r}")
    return ingest


def resolve_ingest(ingest) -> str:
    """Resolve ``ingest='auto'`` to the path that runs, per backend.

    Committed decision rule (BENCH_INGEST=1, the r8/r12 measured-adopt
    discipline): the slabbed path joins ``'auto'`` on a platform only
    where its measured slab-vs-mono ingest ratio on the >= 1 GB proxy
    reaches the 1.2x adopt bar.  The CPU proxy is a **measured
    rejection** (BASELINE.md r22): median mono/slab = 1.04x on the
    1 GiB single-core box — both paths bottleneck on the same host
    memcpy bandwidth, and with one core the double-buffered schedule
    has nothing to overlap against, so slab is parity, not a win.
    Hence
    'auto' -> 'mono' on CPU.  Accelerators keep 'auto' -> 'slab':
    the per-slab ``device_put``s hand copies to the DMA engine, which
    genuinely runs concurrently with the host slicing the next slab
    (the hardware row pins the ratio at the headline shape, same
    decision rule).  Explicit modes pass through untouched — both
    paths assemble byte-identical arrays, so forcing either is always
    safe and ``'mono'`` stays the reachable parity oracle.
    """
    if ingest == "auto":
        return "mono" if jax.default_backend() == "cpu" else "slab"
    return ingest


def _shard_ranges(sharding, global_shape) -> list:
    """Per-addressable-shard placement plan: ``[(lo, hi, [devices])]``
    sorted by row range.  Devices sharing a row range (tensor-parallel
    replication along the model axis) group together — each still
    receives its own copy of the slice."""
    by_range = {}
    for dev, idx in sharding.addressable_devices_indices_map(
            tuple(global_shape)).items():
        rows = idx[0]
        lo = rows.start or 0
        hi = rows.stop if rows.stop is not None else global_shape[0]
        by_range.setdefault((lo, hi), []).append(dev)
    return [(lo, hi, devs) for (lo, hi), devs in
            sorted(by_range.items())]


def _x_slice(x: np.ndarray, lo: int, hi: int, n: int) -> np.ndarray:
    """Rows [lo, hi) of the padded point matrix: a VIEW of ``x`` for
    fully-real ranges (no host copy — the pad-last-slab contract), a
    freshly zero-padded buffer only where the range crosses ``n``."""
    if hi <= n:
        return x[lo:hi]
    out = np.zeros((hi - lo, x.shape[1]), dtype=x.dtype)
    if lo < n:
        out[: n - lo] = x[lo:n]
    return out


def _w_slice(sw: Optional[np.ndarray], lo: int, hi: int, n: int,
             dtype) -> np.ndarray:
    """Rows [lo, hi) of the padded weight vector.  With explicit
    ``sample_weight`` the fully-real ranges are VIEWS of the validated
    weight array (ISSUE 18 satellite: the weighted path used to build a
    full-size ones buffer even when already aligned); padding tails are
    zeros, unweighted ranges ones."""
    if sw is not None and hi <= n:
        return sw[lo:hi]
    if hi <= n:
        return np.ones(hi - lo, dtype=dtype)
    out = np.zeros(hi - lo, dtype=dtype)
    if lo < n:
        out[: n - lo] = 1.0 if sw is None else sw[lo:n]
    return out


def _mono_place(x, sw, n, n_pad, xsh, wsh, dtype):
    """The monolithic parity-oracle placement: one blocking per-shard
    assembly per array; shard slices are host views except the final
    padded tail (``_x_slice``/``_w_slice``)."""
    d = x.shape[1]

    def x_cb(index):
        rows = index[0]
        return np.ascontiguousarray(_x_slice(
            x, rows.start or 0,
            rows.stop if rows.stop is not None else n_pad, n))

    def w_cb(index):
        rows = index[0]
        return np.ascontiguousarray(_w_slice(
            sw, rows.start or 0,
            rows.stop if rows.stop is not None else n_pad, n, dtype))

    # Nested 'stage' span (no ``slab`` attr: this IS the unstaged
    # oracle) — the blocking assembly lands on the ingest timeline
    # like every placement body (the ingest-span rule).
    with _obs_trace.span("stage", ingest="mono", rows=int(n_pad),
                         bytes=int(n_pad) * d * x.itemsize):
        points = jax.make_array_from_callback((n_pad, d), xsh, x_cb)
        weights = jax.make_array_from_callback((n_pad,), wsh, w_cb)
    return points, weights


def _slab_place(x, sw, n, n_pad, xsh, wsh, dtype, chunk_size: int,
                data_shards: int):
    """The slab-staged placement (ISSUE 18 tentpole): device shards
    grouped into HBM-planner-sized slabs (``obs.memory.plan_ingest``),
    each slab's per-device buffers uploaded with async ``device_put``
    and assembled once via ``make_array_from_single_device_arrays``.
    Double-buffered: slab i's completion is awaited only AFTER slab
    i+1's host->device copies are in flight, so transfer and completion
    overlap while at most two slabs' buffers stay pinned."""
    from kmeans_tpu.obs.memory import plan_ingest
    d = x.shape[1]
    plan = plan_ingest(n_pad, d, data_shards=data_shards,
                       chunk=chunk_size, dtype=dtype)
    ranges = _shard_ranges(xsh, (n_pad, d))
    w_devs = {}
    for lo, hi, devs in _shard_ranges(wsh, (n_pad,)):
        w_devs[(lo, hi)] = devs
    g = plan["slab_shards"]
    slabs = [ranges[i: i + g] for i in range(0, len(ranges), g)]
    x_parts, w_parts = [], []
    pending = []
    for i, slab in enumerate(slabs):
        rows = sum(hi - lo for lo, hi, _ in slab)
        # Per-slab 'stage' span (ISSUE 18 satellite): the TTFI table
        # attributes ingest cost slab by slab instead of one opaque
        # stage row.
        with _obs_trace.span("stage", slab=i, slabs=len(slabs),
                             rows=rows, bytes=rows * d * x.itemsize):
            cur = []
            for lo, hi, devs in slab:
                xs = _x_slice(x, lo, hi, n)
                ws = _w_slice(sw, lo, hi, n, dtype)
                for dev in devs:
                    cur.append(jax.device_put(xs, dev))
                    x_parts.append(cur[-1])
                for dev in w_devs[(lo, hi)]:
                    cur.append(jax.device_put(ws, dev))
                    w_parts.append(cur[-1])
            # Await the PREVIOUS slab only now, with this slab's copies
            # already in flight — the double-buffer schedule.
            for arr in pending:
                arr.block_until_ready()
            pending = cur
    for arr in pending:
        arr.block_until_ready()
    _obs_metrics.REGISTRY.counter("ingest.slabs").inc(len(slabs))
    points = jax.make_array_from_single_device_arrays(
        (n_pad, d), xsh, x_parts)
    weights = jax.make_array_from_single_device_arrays(
        (n_pad,), wsh, w_parts)
    return points, weights


def shard_points(x: np.ndarray, mesh: Optional[Mesh], chunk_size: int,
                 sample_weight: Optional[np.ndarray] = None,
                 min_rows: int = 0,
                 ingest: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Pad and place (points, weights) sharded along the mesh's data axis.

    ``sample_weight`` (n,) is folded into the padding mask (padding rows stay
    0).  With ``mesh=None`` the arrays are committed to the default device —
    the single-chip path, same downstream code.  ``min_rows`` raises the
    padding target to a shape-bucket boundary (ISSUE 15b; extra rows are
    inert zero-weight sentinels like all shard padding).

    ``ingest`` (ISSUE 18) picks the placement path: ``'mono'`` — one
    blocking per-shard assembly, the pinned parity oracle; ``'slab'`` —
    shards grouped into HBM-planner-sized slabs uploaded double-buffered
    so consecutive slabs' host->device copies overlap; ``'auto'`` — the
    committed BENCH_INGEST decision rule.  Either path pads only the
    FINAL shard's tail (real-row slices are host views), so the old
    full-dataset host pad copy is gone, and the assembled array is
    byte-identical across modes.
    """
    data_shards, _ = mesh_shape(mesh)
    x = np.asarray(x)
    n = int(x.shape[0])
    mode = resolve_ingest(check_ingest(ingest))
    # 'stage' span (ISSUE 11): one host->device staging of a block —
    # under a prefetched stream these come from the producer thread's
    # own tid, so the chrome timeline shows transfer overlapping the
    # consumer's dispatches.  Slabbed placements nest per-slab 'stage'
    # children under it (self-time accounting keeps the TTFI ladder
    # double-count-free).
    with _obs_trace.span("stage", rows=n, bytes=int(x.nbytes),
                         ingest=mode):
        _obs_metrics.REGISTRY.counter("ingest.bytes").inc(int(x.nbytes))
        if mesh is None:
            x_pad, w_pad = pad_points(x, chunk_size, min_rows=min_rows)
            if sample_weight is not None:
                w_pad[:n] *= sample_weight.astype(w_pad.dtype)
            _obs_metrics.REGISTRY.counter("ingest.slabs").inc()
            return jnp.asarray(x_pad), jnp.asarray(w_pad)
        target = max(n, int(min_rows))
        mult = data_shards * chunk_size
        n_pad = target + ((-target) % mult)
        sw = None
        if sample_weight is not None:
            sw = np.asarray(sample_weight, dtype=x.dtype)
        xsh = NamedSharding(mesh, P(DATA_AXIS, None))
        wsh = NamedSharding(mesh, P(DATA_AXIS))
        if mode == "slab":
            return _slab_place(x, sw, n, n_pad, xsh, wsh, x.dtype,
                               chunk_size, data_shards)
        _obs_metrics.REGISTRY.counter("ingest.slabs").inc()
        return _mono_place(x, sw, n, n_pad, xsh, wsh, x.dtype)



def _validate_sample_weight(sample_weight, n: int, dtype) -> np.ndarray:
    """Shared validation for every weight entry point: shape (n,), finite,
    non-negative; cast to the dataset dtype."""
    sw = np.asarray(sample_weight, dtype=dtype)
    if sw.shape != (n,):
        raise ValueError(
            f"sample_weight must have shape ({n},), got {sw.shape}")
    if np.any(sw < 0) or not np.all(np.isfinite(sw)):
        raise ValueError("sample_weight must be finite and >= 0")
    return sw


class ShardedDataset:
    """Device-resident, mesh-sharded points — the ``rdd.cache()`` analogue.

    The reference re-reads its cached RDD every pass but pays Spark's
    broadcast/shuffle machinery per iteration (kmeans_spark.py:256);
    here the padded (points, weights) arrays are uploaded ONCE, stay sharded
    on the mesh's data axis for their whole lifetime, and every
    fit/predict/score against them is pure device compute.  Keeping a
    host-side reference (when constructed from a NumPy array) makes
    row-sampling — Forgy init (kmeans_spark.py:72) and empty-cluster
    resampling (:196) — free instead of a device gather.
    """

    def __init__(self, points: jax.Array, weights: jax.Array, n: int,
                 chunk: int, mesh: Optional[Mesh],
                 host: Optional[np.ndarray] = None,
                 host_weights: Optional[np.ndarray] = None,
                 local_rows: Optional[int] = None,
                 explicit_chunk: bool = False):
        self.points = points
        self.weights = weights
        self.n = n
        self.d = points.shape[1]
        self.chunk = chunk
        self.mesh = mesh
        # True when the chunk came from a user-supplied ``chunk_size``
        # (loader kwarg or model attribute) rather than the auto rule:
        # fits must then honor it verbatim — the documented escape
        # hatch from the auto rule — so ``effective_chunk`` no-ops.
        self.explicit_chunk = explicit_chunk
        self._host = host
        self._host_weights = host_weights
        # REAL rows THIS process contributed (multi-host process-local
        # loading): this process's real data occupies the first
        # ``local_rows`` rows of its own contiguous padded block, which
        # is what lets ``predict`` unpad per process (r3 VERDICT #4).
        # Defaults to n for fully-addressable datasets; None means the
        # per-process layout is unknown (hand-built global arrays).
        self.local_rows = (local_rows if local_rows is not None
                           else (n if points.is_fully_addressable else None))

    @property
    def dtype(self):
        return np.dtype(str(self.points.dtype))

    def effective_chunk(self, k: int,
                        budget_elems: int = SINGLE_CHUNK_ELEMS,
                        max_chunk: Optional[int] = None) -> int:
        """The chunk fits should scan this dataset with for a model of
        ``k`` clusters/components: ``self.chunk`` unless that would
        materialize an oversized (chunk, k) tile because the load-time
        ``k_hint`` undershot the real k — then the largest safe divisor
        (clamp_chunk_for_k).  Models pass their real TILE width here —
        k, or k*D for modes staging (chunk, k, D) tensors — instead of
        reading ``.chunk`` directly; EM callers pass their own measured
        ``budget_elems`` (models.gmm.EM_CHUNK_BUDGET) and plateau row
        cap (``max_chunk`` = models.gmm.EM_MAX_CHUNK), so mis-hinted
        foreign datasets land near the measured optimum, not merely
        inside the element budget.  An EXPLICIT user chunk
        (loader/model ``chunk_size``) passes through untouched — it is
        the documented override."""
        if self.explicit_chunk:
            return self.chunk
        return clamp_chunk_for_k(self.chunk, k, budget_elems,
                                 max_chunk=max_chunk)

    @property
    def labelable(self) -> bool:
        """True when per-process labels can be unpadded from a global
        assignment pass: the array is fully addressable, or the
        process-local layout is known (``local_rows``).  The single
        predicate behind ``fit``-time ``labels_`` availability and
        ``predict``'s process-local path — keep them in lockstep."""
        return self.points.is_fully_addressable or self.local_rows is not None

    @property
    def host(self) -> Optional[np.ndarray]:
        """Host copy of the (un-padded) data, when constructed from one."""
        return self._host

    @property
    def host_weights(self) -> Optional[np.ndarray]:
        """Host copy of the per-point sample weights (None = all ones)."""
        return self._host_weights

    def positive_rows(self) -> np.ndarray:
        """Indices of rows with weight > 0 (candidates for seeding and
        empty-cluster resampling — zero-weight rows must never become
        centroids)."""
        if self._host_weights is None:
            # Enforce the invariant HERE (ADVICE r1): for process-local
            # datasets, global row indices don't map onto the interleaved
            # padded device layout, so arange(n) would be wrong — don't
            # rely on every caller being separately guarded.
            self._require_addressable("positive_rows")
            return np.arange(self.n)
        return np.flatnonzero(self._host_weights > 0)

    def _require_addressable(self, op: str) -> None:
        if not self.points.is_fully_addressable:
            raise ValueError(
                f"{op} needs a host copy or a fully-addressable array; on "
                "multi-host process-local datasets use init='kmeans++' "
                "(on-device D2 seeding) or an explicit init array, and "
                "empty_cluster='keep' or 'farthest' (host 'resample' "
                "cannot gather rows)")

    def take(self, idx) -> np.ndarray:
        """Gather rows by global index (all indices must be < n)."""
        if self._host is not None:
            return np.asarray(self._host[idx])
        self._require_addressable("row gather")
        return np.asarray(self.points[np.asarray(idx)])

    def sample_positive_rows(self, m: int, seed_seq) -> np.ndarray:
        """Up to ``m`` distinct positive-weight rows, uniformly, seeded by
        ``seed_seq`` (a ``np.random.SeedSequence``-style entropy list).

        With a host copy: the r1 host draw, bit-for-bit (``default_rng``
        choice over ``positive_rows`` — trajectories of existing fits are
        unchanged).  Without one (device-only or multi-host process-local
        datasets): a seeded on-device Gumbel-argmax draw (``_gumbel_rows``)
        whose result is replicated, so every process sees the same rows —
        this is what makes ``empty_cluster='resample'`` work where the r1
        code had to reject it (r1 VERDICT #6)."""
        if self._host is not None:
            rng = np.random.default_rng(seed_seq)
            candidates = self.positive_rows()
            take = min(m, len(candidates))
            idx = candidates[rng.choice(len(candidates), size=take,
                                        replace=False)]
            return self.take(idx)
        # % 2^31: the derived uint32 must stay an int32-safe jit argument
        # (multi-host workers run without jax_enable_x64).
        seed = int(np.random.SeedSequence(seed_seq).generate_state(1)[0]
                   % (2 ** 31))
        # Cap at the positive-weight population like the host-copy engine:
        # past it, the top-k draw runs out of -inf-masked winners and
        # would install zero-weight rows (lowest-index ones first).
        take = min(m, int(jnp.sum(self.weights > 0)))
        if take == 0:
            return np.empty((0, self.d))
        rows = jax.device_get(_gumbel_rows(self.points, self.weights,
                                           seed, take))
        return np.asarray(rows, dtype=np.float64)

    def with_weights(self, sample_weight: np.ndarray) -> "ShardedDataset":
        """Same device-resident points, different per-point weights.

        Only the small (n,) weight vector is re-placed — the (n, D) points
        array is SHARED with this dataset, so masked subproblems (e.g.
        ``BisectingKMeans`` fitting a 2-means on one cluster's members by
        zero-weighting everyone else) cost one tiny upload instead of a full
        re-shard.  ``sample_weight`` is absolute (it replaces, not scales,
        the current weights); padding rows stay 0.
        """
        self._require_addressable("with_weights")
        sw = _validate_sample_weight(sample_weight, self.n, self.dtype)
        w_pad = np.zeros(self.points.shape[0], dtype=self.dtype)
        w_pad[: self.n] = sw
        # 'stage' span (ISSUE 18 ingest-span rule): even the tiny (n,)
        # weight re-upload is a host->device staging — attributed like
        # every other ingest transfer.
        with _obs_trace.span("stage", rows=int(w_pad.shape[0]),
                             bytes=int(w_pad.nbytes)):
            _obs_metrics.REGISTRY.counter("ingest.bytes").inc(
                int(w_pad.nbytes))
            if self.mesh is None:
                w_dev = jnp.asarray(w_pad)
            else:
                w_dev = jax.device_put(
                    w_pad, NamedSharding(self.mesh, P(DATA_AXIS)))
        return ShardedDataset(self.points, w_dev, self.n, self.chunk,
                              self.mesh, host=self._host, host_weights=sw,
                              explicit_chunk=self.explicit_chunk)

    def reshard(self, mesh: Optional[Mesh],
                chunk: Optional[int] = None) -> "ShardedDataset":
        """Re-place the data on a different mesh / chunking — the
        ``rdd.repartition`` analogue (kmeans_spark.py:418).  Goes through
        the host copy when available, else gathers from device."""
        if self._host is None:
            self._require_addressable("reshard")
        host = self._host if self._host is not None else \
            np.asarray(self.points)[: self.n]
        return to_device(host, mesh, chunk or self.chunk, self.dtype,
                         sample_weight=self._host_weights,
                         explicit=(chunk is not None) or self.explicit_chunk)


def to_device(X, mesh: Optional[Mesh], chunk: int, dtype,
              sample_weight=None, explicit: bool = False,
              min_rows: int = 0, ingest: str = "auto") -> ShardedDataset:
    """Upload (n, D) host data once; pass-through if already a ShardedDataset
    on a compatible (mesh, chunk).

    ``sample_weight`` (n,) folds per-point weights into the padding mask —
    weighted counts/sums/SSE come for free from the same fused step (a
    capability the reference lacks; sklearn-style).  ``min_rows`` is the
    shape-bucket padding target (ISSUE 15b; 0 = exact-shape padding, the
    bit-parity oracle).  ``ingest`` picks the placement path (ISSUE 18;
    see :func:`shard_points`).
    """
    if isinstance(X, ShardedDataset):
        if mesh is not None and X.mesh is not mesh:
            raise ValueError("ShardedDataset was placed on a different mesh")
        if np.dtype(dtype) != X.dtype:
            raise ValueError(f"ShardedDataset dtype {X.dtype} != model "
                             f"dtype {np.dtype(dtype)}")
        if sample_weight is not None:
            raise ValueError("pass sample_weight when caching the dataset, "
                             "not on a pre-built ShardedDataset")
        return X
    X = np.ascontiguousarray(np.asarray(X, dtype=dtype))
    if X.ndim != 2:
        raise ValueError(f"X must be 2-D (n, D), got shape {X.shape}")
    sw = None
    if sample_weight is not None:
        sw = _validate_sample_weight(sample_weight, X.shape[0], X.dtype)
    # 'place' span (ISSUE 11): the one-time dataset upload onto the
    # mesh — the transfer share of time-to-first-iteration (contains
    # the 'stage' span; the TTFI report attributes self time, so the
    # nesting never double-counts).
    with _obs_trace.span("place", rows=int(X.shape[0]),
                         bytes=int(X.nbytes)):
        points, weights = shard_points(X, mesh, chunk, sample_weight=sw,
                                       min_rows=min_rows, ingest=ingest)
    return ShardedDataset(points, weights, X.shape[0], chunk, mesh, host=X,
                          host_weights=sw, explicit_chunk=explicit)


def global_sample_rows(x_source: np.ndarray, n_rows: int, k: int,
                       seed: int) -> np.ndarray:
    """Sample k distinct rows from the global index space, seeded.

    The host-side replacement for ``rdd.takeSample(False, k, seed)``
    (kmeans_spark.py:72) — same capability (without replacement, seeded,
    deterministic), no distributed job needed because sampling happens on the
    original host array before sharding.
    """
    if n_rows < k:
        raise ValueError(
            f"Not enough data points ({n_rows}) to initialize {k} clusters")
    rng = np.random.RandomState(seed)
    idx = rng.choice(n_rows, size=k, replace=False)
    return np.asarray(x_source)[idx]


def process_local_layout(local_counts, local_shards: int,
                         chunk: int) -> Tuple[int, int]:
    """Padded per-process row layout for host-sharded loading.

    Every process must contribute an identically-shaped block (XLA global
    arrays are uniform), so each pads to the LARGEST process's share,
    rounded up so every data shard holds a whole number of scan chunks.
    Returns (rows_per_shard, rows_per_process).
    """
    max_local = int(np.max(np.asarray(local_counts)))
    rows_per_shard = -(-max_local // local_shards)          # ceil
    rows_per_shard = -(-rows_per_shard // chunk) * chunk    # chunk multiple
    rows_per_shard = max(rows_per_shard, chunk)
    return rows_per_shard, rows_per_shard * local_shards


def from_process_local(X_local, mesh: Mesh, *,
                       chunk_size: Optional[int] = None,
                       dtype=np.float32, k_hint: int = 16,
                       sample_weight: Optional[np.ndarray] = None
                       ) -> ShardedDataset:
    """Build a globally-sharded dataset where EACH PROCESS contributes only
    its own rows — no host ever materializes the full array.

    This is the multi-host data path the reference delegates to Spark's
    driver-side ``parallelize`` (kmeans_spark.py:369/418: the driver holds
    all N rows); here each host loads its share and
    ``jax.make_array_from_process_local_data`` assembles the global
    data-axis-sharded array, with per-process padding carried as
    zero-weight rows (invisible to every statistic).

    Single-process: exact equivalent of ``to_device`` (host copy kept).
    Multi-host notes: the result has no host copy, so use
    ``init='kmeans++'`` (on-device D² seeding) or an explicit init array —
    Forgy row-gather needs host data and raises a pointed error.
    ``predict``/``labels_`` on this dataset return THIS process's own
    rows' labels (``local_rows`` records the per-process layout);
    concatenating across processes in process order gives the global
    labels.
    """
    if mesh is None:
        raise ValueError("from_process_local requires a mesh")
    X_local = np.ascontiguousarray(np.asarray(X_local, dtype=dtype))
    if X_local.ndim != 2:
        raise ValueError(f"X_local must be 2-D (n, D), got {X_local.shape}")
    n_local, d = X_local.shape
    data_shards, _ = mesh_shape(mesh)
    if jax.process_count() == 1:
        chunk = chunk_size or choose_chunk_size(
            -(-n_local // max(1, data_shards)), k_hint, d)
        return to_device(X_local, mesh, chunk, dtype,
                         sample_weight=sample_weight,
                         explicit=chunk_size is not None)

    from jax.experimental import multihost_utils
    nproc = jax.process_count()
    if data_shards % nproc:
        raise ValueError(
            f"data axis ({data_shards}) must be divisible by the process "
            f"count ({nproc}) for process-local loading")
    # 'collective' span (ISSUE 13): the one host-side cross-process
    # collective of the data path — covered so it lands on the fleet
    # timeline (the `collective-span` lint rule enforces this class).
    with _obs_trace.span("collective", op="process_allgather",
                         site="from_process_local:counts"):
        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local], dtype=np.int64))).reshape(-1)
    n_global = int(counts.sum())
    # Chunk from the allgathered MAX count — every process must compute the
    # identical chunk (and therefore identical global shape and identical
    # jitted program); deriving it from n_local would diverge on uneven
    # shards.
    local_shards = data_shards // nproc
    chunk = chunk_size or choose_chunk_size(
        -(-int(counts.max()) // local_shards), k_hint, d)
    _, rows_per_proc = process_local_layout(counts, local_shards, chunk)
    x_pad = np.zeros((rows_per_proc, d), dtype=X_local.dtype)
    x_pad[:n_local] = X_local
    w_pad = np.zeros((rows_per_proc,), dtype=X_local.dtype)
    if sample_weight is not None:
        w_pad[:n_local] = _validate_sample_weight(sample_weight, n_local,
                                                  X_local.dtype)
    else:
        w_pad[:n_local] = 1.0
    n_pad_global = rows_per_proc * nproc
    # 'stage' span (ISSUE 18 ingest-span rule): the per-process
    # host->device assembly of the global array.
    with _obs_trace.span("stage", rows=int(rows_per_proc),
                         bytes=int(x_pad.nbytes + w_pad.nbytes)):
        _obs_metrics.REGISTRY.counter("ingest.bytes").inc(
            int(x_pad.nbytes + w_pad.nbytes))
        pts = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(DATA_AXIS, None)), x_pad,
            (n_pad_global, d))
        w = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(DATA_AXIS)), w_pad, (n_pad_global,))
    return ShardedDataset(pts, w, n_global, chunk, mesh,
                          local_rows=n_local,
                          explicit_chunk=chunk_size is not None)
