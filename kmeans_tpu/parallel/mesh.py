"""Device mesh construction.

The reference's "cluster" is Spark local mode with parallelism simulated by
partition count (``repartition(4)`` kmeans_spark.py:418, ``numPartitions``
:568; SURVEY.md §4).  Here the cluster is a ``jax.sharding.Mesh``: the same
code runs on one real TPU chip, a CPU-simulated N-device mesh
(``force_cpu_devices``), or a multi-host slice — XLA routes the collectives
over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"    # shards the N points (DP — the reference's partitions)
MODEL_AXIS = "model"  # shards the k centroids (TP/EP analogue; optional)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable ``shard_map``: newer JAX exposes it as
    ``jax.shard_map(..., check_vma=...)``; on older installs (< 0.6) it
    lives in ``jax.experimental.shard_map`` and the replication-check
    kwarg is named ``check_rep``.  Every kernel builder routes through
    here so the whole SPMD surface works on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(name: str) -> int:
    """Version-portable STATIC mesh-axis size inside a mapped body:
    ``lax.axis_size`` where it exists (newer JAX), else the classic
    ``psum(1, axis)`` idiom, which constant-folds to a Python int at
    trace time on older installs."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return lax.psum(1, name)


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    ``data=None`` uses every device not consumed by ``model``.  A 1-device
    mesh is valid (the single-chip case) — the SPMD step is identical, the
    collectives just become no-ops.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if model <= 0:
        raise ValueError(f"model axis size must be positive, got {model}")
    if n % model != 0:
        raise ValueError(f"{n} devices not divisible by model={model}")
    if data is None:
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, "
                         f"have {n}")
    grid = np.array(devs[: data * model]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def force_cpu_devices(n: Optional[int] = None) -> None:
    """Force the CPU platform with ``n`` virtual devices, re-initializing the
    backend if one is already live.

    ``n=None`` honors an ``--xla_force_host_platform_device_count`` already
    present in ``XLA_FLAGS`` (falling back to 1) so externally configured
    simulations keep working.

    This is the JAX analogue of the reference simulating a cluster with Spark
    local-mode partitions (kmeans_spark.py:418,568): sharding/collective code
    paths run on one machine without ``n`` real chips.  ``jax_num_cpu_devices``
    (not ``XLA_FLAGS``) is used because the config value is re-read every time
    a CPU client is created, whereas the flag is parsed only at first backend
    initialization; the ``clear_backends`` handles a platform plugin already
    registered by the session (e.g. a sitecustomize that imports jax at
    interpreter start).
    """
    import os
    import re

    import jax.extend.backend

    if n is None:
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        n = int(m.group(1)) if m else 1
    if n <= 0:
        raise ValueError(f"device count must be positive, got {n}")
    # In-process only (jax.config, not os.environ): an env write would leak
    # the CPU pin into every subprocess the caller later spawns.
    # clear_backends first: jax_num_cpu_devices refuses to update while a
    # backend is live, and the config is re-read at the next client creation.
    jax.extend.backend.clear_backends()
    jax.config.update("jax_num_cpu_devices", n)
    jax.config.update("jax_platforms", "cpu")


def mesh_shape(mesh: Optional[Mesh]) -> tuple[int, int]:
    """(data, model) axis sizes; (1, 1) for the un-meshed single-device case."""
    if mesh is None:
        return (1, 1)
    return (mesh.shape.get(DATA_AXIS, 1), mesh.shape.get(MODEL_AXIS, 1))
