"""Device mesh construction.

The reference's "cluster" is Spark local mode with parallelism simulated by
partition count (``repartition(4)`` kmeans_spark.py:418, ``numPartitions``
:568; SURVEY.md §4).  Here the cluster is a ``jax.sharding.Mesh``: the same
code runs on one real TPU chip, a CPU-simulated N-device mesh
(``--xla_force_host_platform_device_count``), or a multi-host slice — XLA
routes the collectives over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"    # shards the N points (DP — the reference's partitions)
MODEL_AXIS = "model"  # shards the k centroids (TP/EP analogue; optional)


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    ``data=None`` uses every device not consumed by ``model``.  A 1-device
    mesh is valid (the single-chip case) — the SPMD step is identical, the
    collectives just become no-ops.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if model <= 0:
        raise ValueError(f"model axis size must be positive, got {model}")
    if n % model != 0:
        raise ValueError(f"{n} devices not divisible by model={model}")
    if data is None:
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, "
                         f"have {n}")
    grid = np.array(devs[: data * model]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def mesh_shape(mesh: Optional[Mesh]) -> tuple[int, int]:
    """(data, model) axis sizes; (1, 1) for the un-meshed single-device case."""
    if mesh is None:
        return (1, 1)
    return (mesh.shape.get(DATA_AXIS, 1), mesh.shape.get(MODEL_AXIS, 1))
