"""The SPMD training/predict step: ``shard_map`` over a (data, model) mesh.

This is the heart of the Spark replacement (SURVEY.md §2.3 backend row): the
reference's per-iteration communication pattern — broadcast centroids out
(kmeans_spark.py:268), keyed partial-sum shuffle (:169-171), gather to driver
(:173), optional scalar all-reduce for SSE (:237) — collapses into ONE jitted
step whose only collectives are a ``psum`` of a dense (k, D+1) accumulator and
(for the farthest-point policy) a tiny ``all_gather`` of per-shard candidates.
The psum result is replicated on every shard, so the reference's
driver-gather/re-broadcast round-trip disappears entirely.

Axes:
* ``data`` — points sharded on N.  The reference's only parallelism
  (partition count, kmeans_spark.py:418/568) and the moral equivalent of
  sequence/context parallelism for this workload (SURVEY.md §5: the long axis
  IS N; no attention -> no ring schedule obligation).
* ``model`` — centroids sharded on k (row-block).  Beyond-reference TP/EP
  capability for large k*D tables: each shard scores points against its
  centroid block only; the global argmin is reconstructed from an
  ``all_gather`` of per-block minima over the model axis.  Tie-breaking
  remains "global lowest index" because blocks are ordered and both argmins
  pick lowest-first.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kmeans_tpu.obs import trace as _obs_trace
from kmeans_tpu.ops.assign import (GUARDED_MODE, StepStats, _accum_dtype,
                                   accumulate_chunk, consume_chunk,
                                   distance_stage, guarded_assign_chunk,
                                   init_stats, margin_chunk,
                                   pairwise_sq_dists, value_mode)
from kmeans_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, axis_size,
                                      mesh_shape, shard_map)

# Sentinel coordinate for centroid-table padding rows (when k doesn't divide
# the model axis).  Large enough that no real point ever selects a padding
# row, small enough that its squared norm stays finite in float32.
PAD_CENTROID_VALUE = 1e12


def pad_centroids(centroids: np.ndarray, model_shards: int) -> np.ndarray:
    """Pad the (k, D) table to a multiple of the model axis with sentinels."""
    k = centroids.shape[0]
    pad = (-k) % model_shards
    if pad == 0:
        return centroids
    filler = np.full((pad, centroids.shape[1]), PAD_CENTROID_VALUE,
                     dtype=centroids.dtype)
    return np.concatenate([centroids, filler], axis=0)


def _model_axis_select(model_shards: int):
    """select_fn for accumulate_chunk: reconstruct the global argmin across
    the model (centroid-sharded) axis.  Tie-breaking stays "global lowest
    index": argmin over the gathered per-shard minima picks the lowest shard,
    and each shard's local argmin picks its lowest local index."""
    if model_shards <= 1:
        return None
    m_idx = lax.axis_index(MODEL_AXIS)

    def select(best_local, mind2_local):
        minds = lax.all_gather(mind2_local, MODEL_AXIS)     # (m, c)
        owner = jnp.argmin(minds, axis=0)
        return owner == m_idx, jnp.min(minds, axis=0)

    return select


def _model_axis_pair_select(model_shards: int, k_local: int):
    """select_fn for the K-SHARDED statistics pass (ISSUE 16): reconstruct
    the global argmin with a logical (distance, index) pair all-reduce
    instead of the dense ``all_gather`` of per-shard minima — O(chunk)
    payload per collective instead of O(model_shards * chunk), and no
    (m, chunk) gathered tile resident.

    Two ``pmin`` legs realize the pair: the distance leg computes the
    global min; the index leg carries each shard's GLOBAL candidate index
    masked to INT32_MAX wherever that shard did not achieve the min, so
    its pmin is the lowest global index among the achieving shards.
    Tie-breaking is therefore "global lowest index" — bit-identical to
    ``_model_axis_select`` (argmin over gathered minima picks the lowest
    shard, blocks are ordered) and to the dense single-table argmin.
    Ownership is exclusive: a shard owns a row iff the winning index lies
    in its own block, and the winner's index lies in exactly one block."""
    if model_shards <= 1:
        return None
    m_idx = lax.axis_index(MODEL_AXIS)

    def select(best_local, mind2_local):
        gmin = lax.pmin(mind2_local, MODEL_AXIS)
        gidx = (m_idx * k_local + best_local).astype(jnp.int32)
        cand = jnp.where(mind2_local == gmin, gidx,
                         jnp.int32(np.iinfo(np.int32).max))
        win = lax.pmin(cand, MODEL_AXIS)
        return win == gidx, gmin

    return select


PALLAS_MODES = ("pallas", "pallas_bf16")


def _weighted_sqnorm_total(points, weights):
    """The loop-invariant first term of _sse_from_stats:
    ``sum_i w_i ||x_i||^2`` over the shard's RAW (un-prepped) rows."""
    return jnp.sum(weights.astype(jnp.float32)
                   * jnp.sum(points.astype(jnp.float32) ** 2, axis=1))


def _sse_from_stats(x2w, centroids, sums, counts, acc):
    """SSE derived algebraically from the pass statistics:

        SSE = sum_i w_i ||x_i||^2  -  2 sum_k <c_k, S_k>  +  sum_k n_k ||c_k||^2

    (expand ||x - c_{b(i)}||^2 and group by cluster; S_k / n_k are the
    weighted per-cluster coordinate sums and counts).  ``x2w`` is the
    loop-INVARIANT first term — callers compute it once per fit.  This
    costs O(k*D) instead of an O(n) reduce over the kernel's per-point
    mind2 output, whose HBM layout-conversion copy alone is ~0.3 ms/iter
    at 400k points; an in-kernel SSE accumulator was measured even more
    expensive (~1 ms/iter — it chains the sequential grid).  Clamped at 0:
    the difference of large terms can go tiny-negative near a perfect
    fit.  Accuracy: the same bf16-product class as the kernel's distances
    (sums carry bf16-rounded products), plus cancellation amplification
    when SSE << x2w; the convergence-history use cares about neither."""
    c = centroids.astype(jnp.float32)
    cross = jnp.sum(c * sums.astype(jnp.float32))
    cnorm = jnp.sum(counts.astype(jnp.float32) * jnp.sum(c * c, axis=1))
    return jnp.maximum(x2w - 2.0 * cross + cnorm, 0.0).astype(acc)


def _pallas_local_stats(points, weights, centroids_block, *, mode: str,
                        model_shards: int = 1, chunk_size: int = 512,
                        need_sse: bool = True, need_farthest: bool = True,
                        need_sse_pc: bool = True, x2w=None, w_col=None):
    """Shard-local pass via the fused Pallas kernel (ops.pallas_kernels):
    one Mosaic kernel per shard instead of the XLA scan.  f32 compute
    (bf16 matmuls for 'pallas_bf16'); falls back to the Pallas interpreter
    off-TPU so the same code path is CI-testable.

    The ``need_*`` flags elide the optional statistics' XLA-side work
    (r2: the unconditional per-cluster ``segment_sum`` was real per-pass
    VPU cost the on-device fit loop never consumed); elided fields keep
    their ``init_stats`` values exactly like the XLA path's.

    Under centroid (model-axis) sharding the kernel runs in its
    assignment-only form (``pallas_assign``): the GLOBAL argmin is
    reconstructed from an all_gather of per-block minima, then the one-hot
    accumulation runs as an ownership-masked XLA chunk scan — fusing it
    into the kernel against the LOCAL block would mis-accumulate points
    whose true winner lives in another shard's block (r1 VERDICT #3)."""
    from kmeans_tpu.ops.pallas_kernels import (fused_assign_reduce,
                                               pallas_assign)
    acc = _accum_dtype(points.dtype)
    interpret = jax.default_backend() != "tpu"
    bf16 = (mode == "pallas_bf16")
    k_local, d = centroids_block.shape
    w = weights.astype(jnp.float32)
    if model_shards <= 1:
        # Per-point mind2 is only materialized when something reads it:
        # farthest tracking, per-cluster SSE, or an SSE without the
        # precomputed invariant term.
        need_point = (need_farthest or need_sse_pc
                      or (need_sse and x2w is None))
        labels, gmind2, sums, counts = fused_assign_reduce(
            points, w_col if w_col is not None else weights,
            centroids_block, bf16=bf16, interpret=interpret,
            with_mind2=need_point)
        w_eff = w
    else:
        labels, mind2 = pallas_assign(points, centroids_block, bf16=bf16,
                                      interpret=interpret)
        minds = lax.all_gather(mind2, MODEL_AXIS)          # (m, n_local)
        owner = jnp.argmin(minds, axis=0)
        gmind2 = jnp.min(minds, axis=0)
        m_idx = lax.axis_index(MODEL_AXIS)
        w_eff = w * (owner == m_idx)                       # ownership mask
        # Prepped points (width != d) carry lane padding + a constant-1
        # fold column at lane d: the scatter matmul's lane-d output
        # column then IS the weighted counts (no separate VPU sum), and
        # rows are a PREP_ROW_MULTIPLE multiple (chunk_size need not
        # divide them).
        from kmeans_tpu.ops.pallas_kernels import PREP_ROW_MULTIPLE
        n_loc, d_in = points.shape
        fold = d_in != d
        acc_chunk = (chunk_size if n_loc % chunk_size == 0
                     else PREP_ROW_MULTIPLE)
        n_chunks = n_loc // acc_chunk
        xs = (points.reshape(n_chunks, acc_chunk, d_in),
              labels.reshape(n_chunks, acc_chunk),
              w_eff.reshape(n_chunks, acc_chunk))
        ids = jnp.arange(k_local, dtype=labels.dtype)

        def body(carry, chk):
            s, cnt = carry
            xc, lc, wc = chk
            oh = (lc[:, None] == ids[None, :]) * wc[:, None]
            s = s + lax.dot_general(oh, xc.astype(jnp.float32),
                                    (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if not fold:
                cnt = cnt + jnp.sum(oh, axis=0)
            return (s, cnt), None

        (sums, counts), _ = lax.scan(
            body, (jnp.zeros((k_local, d_in), jnp.float32),
                   jnp.zeros((k_local,), jnp.float32)), xs)
        if fold:
            counts = sums[:, d]
            sums = sums[:, :d]
    zero = init_stats(k_local, d, acc)
    if not need_sse:
        sse = zero.sse
    elif x2w is not None and model_shards <= 1:
        sse = _sse_from_stats(x2w, centroids_block, sums, counts, acc)
    else:
        sse = jnp.sum(gmind2 * w).astype(acc)    # global min: /m later
    sse_pc = (jax.ops.segment_sum(        # ownership-masked: psum-safe
        gmind2 * w_eff, labels, num_segments=k_local).astype(acc)
        if need_sse_pc else zero.sse_per_cluster)
    if need_farthest:
        masked = jnp.where(w > 0, gmind2, -jnp.inf)
        i = jnp.argmax(masked)
        far_d = jnp.where(jnp.any(w > 0), masked[i], -1.0).astype(acc)
        far_p = points[i, :d].astype(acc)    # [:d]: prepped points carry
    else:                                    # lane padding + fold column
        far_d, far_p = zero.farthest_dist, zero.farthest_point
    return StepStats(sums.astype(acc), counts.astype(acc), sse, far_d,
                     far_p, sse_pc), labels


def _local_stats(points, weights, centroids_block, *, chunk_size, mode,
                 model_shards: int, need_sse: bool = True,
                 need_farthest: bool = True, need_sse_pc: bool = True,
                 x2w=None, w_col=None, pipeline: int = 0,
                 real_mask=None, kshard: bool = False):
    """Per-(data,model)-shard pass: scan chunks via the shared
    stage-A/stage-B body (``ops.assign.distance_stage``/``consume_chunk``;
    one fused Pallas kernel for the 'pallas' modes).  Returns
    ``(StepStats, corrected)`` — ``corrected`` is the shard-local
    bf16-guard audit count (constant 0 for unguarded modes).  Returned
    ``sums``/``counts`` cover only this shard's centroid block (embedded
    later); ``sse``/farthest use the GLOBAL min distance reconstructed
    across the model axis.  The ``need_*`` flags elide the optional
    statistics' compute (see ``consume_chunk``).

    ``pipeline`` selects the chunk schedule (ISSUE 8, the r8
    ``gmm_step._chunked_epass`` discipline applied to the Lloyd E-step):
    ``0`` runs stage A (the (chunk, k) distance matmul, MXU) and stage B
    (argmin + one-hot scatter + stat folds, VPU + MXU epilogue)
    back-to-back per chunk — the bit-exact parity oracle.  ``1`` skews
    the schedule one chunk: a prologue computes chunk 0's distance tile
    outside the scan, each scan step then runs stage A for chunk i and
    stage B for chunk i-1 (no data dependency between the two inside a
    step, so XLA can overlap the VPU argmin/scatter epilogue with the
    next chunk's MXU matmul), and an epilogue drains the final in-flight
    tile.  Per chunk the arithmetic and the fold order of the statistics
    are IDENTICAL to the serial body — the schedules are bit-exact
    parity partners (pinned, tests/test_lloyd_pipeline.py).  The Pallas
    modes ignore ``pipeline`` (the fused kernel already owns its own
    overlap schedule); a single-chunk shard degenerates to the serial
    body (prologue + epilogue with an empty scan is the same program).
    """
    if mode in PALLAS_MODES:
        st = _pallas_local_stats(points, weights, centroids_block,
                                 mode=mode, model_shards=model_shards,
                                 chunk_size=chunk_size,
                                 need_sse=need_sse,
                                 need_farthest=need_farthest,
                                 need_sse_pc=need_sse_pc, x2w=x2w,
                                 w_col=w_col)[0]
        return st, jnp.zeros((), jnp.int32)
    k_local, d = centroids_block.shape
    acc = _accum_dtype(points.dtype)
    n_chunks = points.shape[0] // chunk_size
    xs = (points.reshape(n_chunks, chunk_size, d),
          weights.astype(acc).reshape(n_chunks, chunk_size))
    # kshard swaps the dense (m, chunk) minima gather for the pair
    # all-reduce (ISSUE 16); both selects return the identical global min
    # and the identical "global lowest index" owner, so every downstream
    # statistic is bit-equal — only the collective pattern differs.
    select = (_model_axis_pair_select(model_shards, k_local) if kshard
              else _model_axis_select(model_shards))
    kw = dict(mode=mode, select_fn=select, need_sse=need_sse,
              need_farthest=need_farthest, need_sse_pc=need_sse_pc,
              real_mask=real_mask)
    init = (init_stats(k_local, d, acc), jnp.zeros((), jnp.int32))

    if not pipeline or n_chunks == 1:
        def body(carry, chunk):
            st, nc = carry
            xc, wc = chunk
            st, c = consume_chunk(
                st, distance_stage(xc, centroids_block, mode=mode),
                xc, wc, centroids_block, **kw)
            return (st, nc + c), None

        (stats, corrected), _ = lax.scan(body, init, xs)
        return stats, corrected

    # Prologue: stage A for chunk 0 (fills the one-chunk in-flight tile).
    x0, w0 = xs[0][0], xs[1][0]
    rest = (xs[0][1:], xs[1][1:])

    def body(carry, chunk):
        st, nc, d2_prev, x_prev, w_prev = carry
        xc, wc = chunk
        d2_c = distance_stage(xc, centroids_block, mode=mode)  # A, chunk i
        st, c = consume_chunk(st, d2_prev, x_prev, w_prev,
                              centroids_block, **kw)           # B, i-1
        return (st, nc + c, d2_c, xc, wc), None

    carry0 = init + (distance_stage(x0, centroids_block, mode=mode),
                     x0, w0)
    (st, nc, d2_last, x_last, w_last), _ = lax.scan(body, carry0, rest)
    # Epilogue: stage B for the final in-flight chunk.
    st, c = consume_chunk(st, d2_last, x_last, w_last, centroids_block,
                          **kw)
    return st, nc + c


def _check_guarded(mode: str, model_shards: int,
                   empty_policy: Optional[str] = None) -> None:
    """Builder-level support matrix of the guarded bf16 rung (ISSUE 8)."""
    if mode != GUARDED_MODE:
        return
    if model_shards > 1:
        raise ValueError(
            "distance_mode='matmul_bf16_guarded' requires a data-parallel "
            "mesh (model_shards == 1): the guard re-resolves near-tie "
            "rows against a full-precision distance pass, which has no "
            "TP (centroid-sharded) form — the same rejection the serving "
            "engine applies to quantize='bf16' under TP sharding")
    if empty_policy == "farthest":
        raise ValueError(
            "distance_mode='matmul_bf16_guarded' does not support "
            "empty_cluster='farthest': the farthest-point policy is an "
            "argmax over min-distance VALUES, which the guarded rung "
            "reproduces only to ~1 ulp (the rtol class), not bitwise; "
            "use 'keep' or 'resample' (label-exact by construction)")


@_obs_trace.traced_builder
def make_step_fn(mesh: Mesh, *, chunk_size: int,
                 mode: str = "matmul", pipeline: int = 0) -> Callable:
    """Build the jitted SPMD step: (points, weights, centroids) -> StepStats.

    ``points``/``weights`` sharded P(data)/P(data); ``centroids`` sharded
    P(model) on k (replicated when the model axis is size 1).  All returned
    stats are fully replicated — every host can run the convergence check
    identically, exactly like the reference's driver but with no gather
    (SURVEY.md §5 backend mapping).  ``pipeline`` selects the chunk
    schedule (``_local_stats``; bit-exact parity partners).  The guarded
    bf16 rung is supported on data-parallel meshes (labels/sums/counts
    bit-equal to 'matmul'; its per-dispatch guard audit is not surfaced
    here — the device fit loops carry it).
    """
    data_shards, model_shards = mesh_shape(mesh)
    _check_guarded(mode, model_shards)

    def step(points, weights, centroids_block):
        k_local, d = centroids_block.shape
        x2w = None
        if mode in PALLAS_MODES and model_shards <= 1:
            # Algebraic SSE term (see _sse_from_stats).  On THIS per-
            # dispatch path the motivation is accuracy and host/device
            # loop consistency, not speed: the extra O(n*D) reduce here
            # is NOT loop-invariant-hoisted (~1 ms/iter at 2M x 128) but
            # it avoids the min-over-noisy-distances LOW BIAS of the
            # per-point SSE under bf16-rate products (measured 6.5% low
            # on separated blobs vs 1.2e-6 relative for this form), and
            # is <2% of the ~100 ms host-loop dispatch RTT it rides on.
            x2w = _weighted_sqnorm_total(points, weights)
        st, _ = _local_stats(points, weights, centroids_block,
                             chunk_size=chunk_size, mode=mode,
                             model_shards=model_shards, x2w=x2w,
                             pipeline=pipeline)
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        # Embed this shard's centroid block into the full table, then one
        # psum over BOTH axes yields replicated global sums/counts.
        k = k_local * model_shards
        off = jnp.asarray(m_idx * k_local, jnp.int32)
        sums_full = lax.dynamic_update_slice(
            jnp.zeros((k, d), st.sums.dtype), st.sums,
            (off, jnp.int32(0)))
        counts_full = lax.dynamic_update_slice(
            jnp.zeros((k,), st.counts.dtype), st.counts, (off,))
        sse_pc_full = lax.dynamic_update_slice(
            jnp.zeros((k,), st.sse_per_cluster.dtype), st.sse_per_cluster,
            (off,))
        axes = (DATA_AXIS, MODEL_AXIS)
        sums_full = lax.psum(sums_full, axes)
        counts_full = lax.psum(counts_full, axes)
        # Ownership-masked per shard -> a plain psum, no double-count.
        sse_pc_full = lax.psum(sse_pc_full, axes)
        # sse is identical on every model shard -> divide the double-count out.
        sse = lax.psum(st.sse, axes) / model_shards
        # Farthest point: gather the per-shard candidates, take the argmax —
        # deterministic (first max wins), no averaging of tied points.
        far_ds = lax.all_gather(st.farthest_dist, axes)        # (ndev,)
        far_ps = lax.all_gather(st.farthest_point, axes)       # (ndev, D)
        j = jnp.argmax(far_ds)
        return StepStats(sums_full, counts_full, sse, far_ds[j], far_ps[j],
                         sse_pc_full)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None)),
        out_specs=StepStats(P(None, None), P(None), P(), P(), P(None),
                            P(None)),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_kshard_step_fn(mesh: Mesh, *, chunk_size: int,
                        mode: str = "matmul",
                        pipeline: int = 0) -> Callable:
    """K-SHARDED variant of ``make_step_fn`` for the massive-k tier
    (ISSUE 16): per-cluster ``sums``/``counts``/``sse_per_cluster`` stay
    SHARDED on the model axis (``P(MODEL_AXIS, ...)`` out_specs) instead
    of being embedded into a replicated full table, and the assignment
    pass reconstructs the global argmin with the (distance, index) pair
    all-reduce (``_model_axis_pair_select``) instead of the dense
    ``all_gather`` of per-shard minima.

    What that buys at large k: the dense TP step materializes a
    replicated (k, D) psum accumulator (plus counts and the gathered
    (m, chunk) minima tile) on EVERY device — the exact term the r16
    planner's ``k_shard`` branch removes; here no device ever holds more
    than its (k/M, D) block of the statistics.  The host M-step is
    unchanged: ``np.asarray`` on the sharded stats gathers them on the
    host, where the float64 division already lives.

    Parity: both selects return the identical global min distance and
    the identical "global lowest index" owner, and the replicated
    ``sse``/farthest reductions reuse the dense expressions verbatim, so
    the k-sharded step is a BIT-EXACT partner of the dense TP step
    (``k_shard=0`` is the oracle; pinned in tests/test_large_k.py).

    Matmul-class modes only: the fused Pallas kernels own their TP form
    (assignment-only + gathered minima), and the guarded bf16 rung is
    already rejected under TP (``_check_guarded``).
    """
    data_shards, model_shards = mesh_shape(mesh)
    if model_shards <= 1:
        raise ValueError(
            "make_kshard_step_fn requires a TP (centroid-sharded) mesh "
            f"(model_shards > 1, got {model_shards}); on a data-parallel "
            "mesh the dense step already holds only one centroid block — "
            "use make_step_fn (k_shard=0)")
    if mode in PALLAS_MODES or mode == GUARDED_MODE:
        raise ValueError(
            f"make_kshard_step_fn supports the matmul-class modes only, "
            f"got {mode!r}: the Pallas kernels carry their own TP "
            "assignment form, and the guarded bf16 rung has no TP form "
            "(_check_guarded)")

    def step(points, weights, centroids_block):
        st, _ = _local_stats(points, weights, centroids_block,
                             chunk_size=chunk_size, mode=mode,
                             model_shards=model_shards,
                             pipeline=pipeline, kshard=True)
        # Per-block stats: psum over the DATA axis only — the model axis
        # is the OUTPUT sharding (out_specs below stitch the blocks into
        # the global (k, D) view the host M-step gathers lazily).
        sums = lax.psum(st.sums, DATA_AXIS)
        counts = lax.psum(st.counts, DATA_AXIS)
        sse_pc = lax.psum(st.sse_per_cluster, DATA_AXIS)
        # sse/farthest reuse the dense-step expressions verbatim (the
        # pair select's gmin is global, so st.sse is identical on every
        # model shard — the same replication the dense step divides out).
        sse = lax.psum(st.sse, (DATA_AXIS, MODEL_AXIS)) / model_shards
        far_ds = lax.all_gather(st.farthest_dist, (DATA_AXIS, MODEL_AXIS))
        far_ps = lax.all_gather(st.farthest_point, (DATA_AXIS, MODEL_AXIS))
        j = jnp.argmax(far_ds)
        return StepStats(sums, counts, sse, far_ds[j], far_ps[j], sse_pc)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None)),
        out_specs=StepStats(P(MODEL_AXIS, None), P(MODEL_AXIS), P(), P(),
                            P(None), P(MODEL_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


def _two_level_best(xc, coarse, cents_ext, members, *, nprobe: int,
                    mode: str, k: int):
    """Per-chunk two-level candidate search (ISSUE 16): route each row
    through the coarse quantizer, then recompute EXACT distances over the
    activated cells' member lists.  Returns ``(best_d, best_i)`` — the
    exact squared distance to, and the GLOBAL index of, the nearest
    candidate centroid.

    Routing: each row activates its ``nprobe`` nearest coarse cells (the
    per-row ``nprobe``-th-smallest threshold; coarse-distance ties
    activate a SUPERSET of cells, which only widens the candidate set).
    A ``fori_loop`` over cells visits only cells some row in the chunk
    activated (``lax.cond`` — inactive cells pay nothing), gathers the
    cell's (L, d) member table from the full table, and computes the
    (chunk, L) distance tile with the SAME ``pairwise_sq_dists`` mode
    ladder as the dense path — distances over the candidate set are
    exact, which is the SSE contract (docs/ANALYSIS.md).

    Tie-breaking matches the dense argmin's "global lowest index": the
    cross-cell merge is lexicographic on (distance, global index), and
    member lists arrive SORTED ascending (the host builder's contract),
    so the within-cell argmin already picks the lowest global index.
    ``members`` entries equal to ``k`` are empty slots (they gather the
    sentinel row of ``cents_ext`` and are masked to +inf); every cell
    must carry >= 1 real member (the host builder seeds empty cells with
    their nearest fine centroid), so ``best_i < k`` for every row."""
    chunk = xc.shape[0]
    C, L = members.shape
    if not 1 <= nprobe <= C:
        raise ValueError(f"nprobe must be in [1, {C}], got {nprobe}")
    dc = pairwise_sq_dists(xc, coarse, mode=mode)           # (chunk, C)
    thresh = -lax.top_k(-dc, nprobe)[0][:, -1]
    active = dc <= thresh[:, None]                          # (chunk, C)
    cell_any = jnp.any(active, axis=0)                      # (C,)
    carry0 = (jnp.full((chunk,), jnp.inf, dc.dtype),
              jnp.full((chunk,), k, jnp.int32))

    def cell(c, carry):
        def visit(carry):
            bd, bi = carry
            mem = members[c]                                # (L,)
            ctab = cents_ext[mem]                           # (L, d)
            d2 = pairwise_sq_dists(xc, ctab, mode=mode)     # (chunk, L)
            valid = (mem < k)[None, :] & active[:, c][:, None]
            d2 = jnp.where(valid, d2, jnp.inf)
            j = jnp.argmin(d2, axis=1)
            dm = jnp.min(d2, axis=1)
            gi = mem[j].astype(jnp.int32)
            better = (dm < bd) | ((dm == bd) & (gi < bi))
            return (jnp.where(better, dm, bd),
                    jnp.where(better, gi, bi))

        return lax.cond(cell_any[c], visit, lambda s: s, carry)

    return lax.fori_loop(0, C, cell, carry0)


def _check_two_level(mode: str, model_shards: int) -> None:
    """Builder-level support matrix of the two-level tier (ISSUE 16)."""
    if model_shards != 1:
        raise ValueError(
            "two-level assignment requires a data-parallel mesh "
            f"(model_shards == 1, got {model_shards}): the candidate "
            "gather indexes the FULL centroid table; at table sizes "
            "that need TP sharding, use k_shard instead (the two tiers "
            "compose with the planner, not with each other)")
    if mode in PALLAS_MODES or mode == GUARDED_MODE:
        raise ValueError(
            f"two-level assignment supports the matmul-class modes only, "
            f"got {mode!r}: the fused Pallas kernels and the guarded "
            "bf16 rung are dense-tile programs — the candidate-set "
            "gather has no fused form")


@_obs_trace.traced_builder
def make_two_level_step_fn(mesh: Mesh, *, chunk_size: int, nprobe: int,
                           mode: str = "matmul") -> Callable:
    """TWO-LEVEL variant of ``make_step_fn`` for the massive-k tier
    (ISSUE 16): ``(points, weights, centroids (k, D), coarse (C, D),
    members (C, L)) -> StepStats``.  The coarse quantizer routes each
    chunk to a bounded candidate set (``_two_level_best``) and the
    per-cluster statistics accumulate by SCATTER-ADD over the winning
    labels — the step never materializes a (chunk, k) dense tile, which
    is the memory wall the r16 planner predicts (docs/PERFORMANCE.md).

    SSE stays EXACT for the produced labeling: distances over the
    candidate set come from the same ``pairwise_sq_dists`` ladder as the
    dense path, and the per-chunk SSE fold is the dense expression
    verbatim.  With ``nprobe == C`` the candidate set covers every
    centroid and the step is a parity partner of the dense step
    (``assign='dense'`` is the oracle; the scatter-add fold order is the
    only difference — the r10 f64 parity class, pinned in
    tests/test_large_k.py).  Matmul-class modes, data-parallel meshes
    only (``_check_two_level``)."""
    data_shards, model_shards = mesh_shape(mesh)
    _check_two_level(mode, model_shards)

    def step(points, weights, centroids, coarse, members):
        k, d = centroids.shape
        acc = _accum_dtype(points.dtype)
        n_chunks = points.shape[0] // chunk_size
        xs = (points.reshape(n_chunks, chunk_size, d),
              weights.astype(acc).reshape(n_chunks, chunk_size))
        cents_ext = jnp.concatenate(
            [centroids, jnp.full((1, d), PAD_CENTROID_VALUE,
                                 centroids.dtype)], axis=0)

        def body(st, chunk):
            xc, wc = chunk
            bd, bi = _two_level_best(xc, coarse, cents_ext, members,
                                     nprobe=nprobe, mode=mode, k=k)
            sums = st.sums.at[bi].add(xc.astype(acc) * wc[:, None])
            counts = st.counts.at[bi].add(wc)
            sse = st.sse + jnp.sum(bd * wc).astype(acc)
            sse_pc = st.sse_per_cluster.at[bi].add((bd * wc).astype(acc))
            masked = jnp.where(wc > 0, bd, -jnp.inf)
            i = jnp.argmax(masked)
            better = masked[i] > st.farthest_dist
            far_d = jnp.where(better, masked[i],
                              st.farthest_dist).astype(acc)
            far_p = jnp.where(better, xc[i].astype(acc),
                              st.farthest_point)
            return StepStats(sums, counts, sse, far_d, far_p, sse_pc), None

        st, _ = lax.scan(body, init_stats(k, d, acc), xs)
        sums = lax.psum(st.sums, DATA_AXIS)
        counts = lax.psum(st.counts, DATA_AXIS)
        sse_pc = lax.psum(st.sse_per_cluster, DATA_AXIS)
        sse = lax.psum(st.sse, DATA_AXIS)
        far_ds = lax.all_gather(st.farthest_dist, DATA_AXIS)
        far_ps = lax.all_gather(st.farthest_point, DATA_AXIS)
        j = jnp.argmax(far_ds)
        return StepStats(sums, counts, sse, far_ds[j], far_ps[j], sse_pc)

    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(None, None),
                  P(None, None), P(None, None)),
        out_specs=StepStats(P(None, None), P(None), P(), P(), P(None),
                            P(None)),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_two_level_predict_fn(mesh: Mesh, *, chunk_size: int, nprobe: int,
                              mode: str = "matmul",
                              donate_points: bool = False) -> Callable:
    """Two-level label assignment (ISSUE 16): ``(points, centroids,
    coarse, members) -> labels`` with labels data-sharded — the serving
    twin of ``make_two_level_step_fn``'s assignment pass, same candidate
    search, same tie-breaking, no (chunk, k) dense tile.
    ``donate_points`` mirrors ``make_predict_fn`` (the serving engine's
    single-use staging buffer)."""
    data_shards, model_shards = mesh_shape(mesh)
    _check_two_level(value_mode(mode), model_shards)
    mode = value_mode(mode)

    def predict(points, centroids, coarse, members):
        k, d = centroids.shape
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)
        cents_ext = jnp.concatenate(
            [centroids, jnp.full((1, d), PAD_CENTROID_VALUE,
                                 centroids.dtype)], axis=0)

        def body(_, xc):
            _, bi = _two_level_best(xc, coarse, cents_ext, members,
                                    nprobe=nprobe, mode=mode, k=k)
            return None, bi

        _, labels = lax.scan(body, None, xs)
        return labels.reshape(-1)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None, None), P(None, None),
                  P(None, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate_points else ())


#: Ordered phase labels of the assignment pass's cumulative-prefix
#: ladder (``make_estep_phase_fn``): 'distance' runs only the (chunk, k)
#: distance matmul (+ a one-pass tile reduction so nothing is DCE'd),
#: 'assign' adds the argmin/min over the tile, 'reduce' adds the one-hot
#: scatter-sum matmul, counts, and the cross-shard (k, D) psum — i.e.
#: the full per-iteration statistics pass.
ESTEP_PHASES = ("distance", "assign", "reduce")


@_obs_trace.traced_builder
def make_estep_phase_fn(mesh: Mesh, *, chunk_size: int, n_iters: int,
                        phase: str, mode: str = "matmul") -> Callable:
    """Phase-prefix iteration chain for the phase-decomposition harness
    (``utils.profiling.measure_phase_ladder``; ISSUE 3 / VERDICT weak
    #8): ``n_iters`` repetitions of ONLY the assignment pass's leading
    phases, all under one dispatch, with a trivial data dependency
    threading the centroid table through the loop so no iteration is
    dead-code-eliminated.  Returns a jitted
    ``(points, weights, centroids_block) -> scalar``; the harness times
    two chain lengths and takes the marginal, then attributes each
    phase the rung-to-rung difference.

    Caveats the harness documents alongside its numbers: the 'distance'
    rung pays one cheap pass over the (chunk, k) tile (a sum) so its
    matmul cannot be elided — the 'assign' - 'distance' difference is
    therefore argmin-minus-sum, a slight undercount of the argmin
    reduction itself; and only the 'reduce' rung carries the per-
    iteration (k, D) psum, so collective/DMA cost lands in that phase.
    Pallas modes fuse all phases inside one kernel and cannot be
    prefix-laddered — the harness ladders the XLA 'matmul' path and
    reports the fused kernel's full-step time next to it."""
    if phase not in ESTEP_PHASES:
        raise ValueError(f"phase must be one of {ESTEP_PHASES}, got "
                         f"{phase!r}")
    if mode in PALLAS_MODES:
        raise ValueError("the fused Pallas kernel has no phase prefixes; "
                         "ladder mode='matmul' and compare the fused "
                         "kernel's full step alongside")
    data_shards, model_shards = mesh_shape(mesh)

    def run(points, weights, centroids_block):
        k_local, d = centroids_block.shape
        acc = _accum_dtype(points.dtype)
        n_chunks = points.shape[0] // chunk_size
        xs = (points.reshape(n_chunks, chunk_size, d),
              weights.astype(acc).reshape(n_chunks, chunk_size))
        select = _model_axis_select(model_shards)
        axes = (DATA_AXIS, MODEL_AXIS)

        def iter_dep(cents):
            if phase == "reduce":
                def body(carry, chunk):
                    xc, wc = chunk
                    return accumulate_chunk(
                        carry, xc, wc, cents, mode=mode, select_fn=select,
                        need_sse=False, need_farthest=False,
                        need_sse_pc=False), None
                st, _ = lax.scan(body, init_stats(k_local, d, acc), xs)
                sums = lax.psum(st.sums, axes)
                counts = lax.psum(st.counts, axes)
                return jnp.sum(sums) + jnp.sum(counts)

            def body(carry, chunk):
                xc, wc = chunk
                d2 = pairwise_sq_dists(xc, cents, mode=mode)
                if phase == "distance":
                    return carry + jnp.sum(d2), None
                best = jnp.argmin(d2, axis=1)
                mind2 = jnp.min(d2, axis=1)
                return carry + jnp.sum(mind2 * wc) \
                    + jnp.sum(best.astype(acc)), None

            dep, _ = lax.scan(body, jnp.zeros((), acc), xs)
            return lax.psum(dep, axes)

        def loop_body(i, cents):
            return cents + 0.0 * iter_dep(cents)

        out = lax.fori_loop(0, n_iters, loop_body,
                            centroids_block.astype(acc))
        return lax.psum(jnp.sum(out), axes) / (data_shards * model_shards)

    mapped = shard_map(
        run, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None)),
        out_specs=P(), check_vma=False)
    return jax.jit(mapped)


def _empty_seed_array(seed: int, iter0: int, max_iter: int) -> np.ndarray:
    """Per-iteration base seeds for the device loops' empty-cluster draws.

    Matches the host path's device sampling engine exactly:
    ``ShardedDataset.sample_positive_rows(m, [seed, iteration + 1])``
    derives ``PRNGKey(SeedSequence([seed, iteration + 1]) % 2**31)``
    (sharding.py:205-210).  SeedSequence is host-only, so the whole
    schedule is precomputed here and passed to the fit functions as a
    TRACED (max_iter,) argument indexed by the loop counter — an
    argument, not a baked constant, so fits differing only by seed share
    one compiled program."""
    return np.asarray(
        [np.random.SeedSequence([seed, iter0 + i + 1]).generate_state(1)[0]
         % (2 ** 31) for i in range(max_iter)], dtype=np.uint32)


def _refill_empty_slots(new, is_empty, skip, points, weights, n_orig, d,
                        seed_i, acc):
    """Refill ALL empty slots in one iteration — the reference samples
    ``len(empty_clusters)`` replacements at once (kmeans_spark.py:196-200)
    and the host path does too (kmeans.py._handle_empty); r2's device
    loops drained one slot per iteration (r2 VERDICT weak #3).

    The draw sequence is bit-identical to the host engine's on-device
    sampler (``sharding._gumbel_rows`` keyed by ``[seed, iteration+1]``,
    which since r5 is ONE Gumbel array + ``top_k``): all of a restart's
    draws share one ``PRNGKey(seed_i)``-seeded Gumbel array over the
    FULL padded global row space, masked to positive-weight rows, and
    draw ``i`` takes the i-th largest score — realized here as
    sequential argmax with winner-remasking over the FIXED array, which
    is exactly top-k order (per-shard argmax picks the lowest local
    index, the gathered argmax picks the lowest shard — together the
    lowest global index, same as top_k's first-occurrence tie rule).
    Each shard generates all ``n_glob`` Gumbel values and slices its own
    segment — the price of bit-matching a draw defined on the global
    index space; the ``fori_loop`` runs ZERO trips on iterations
    without empties, so normal iterations pay nothing.

    ``skip`` (traced 0/1) skips that many leading empty slots — the
    'farthest' policy fills the first empty with the farthest point and
    samples only the rest, exactly like the host path.  ``points`` may be
    the ``prep_points`` output (row order and the first ``d`` lanes of
    the first ``n_orig`` rows are unchanged); ``weights`` must be the
    PRE-prep per-row mask.

    (Thin wrapper: the R=1 specialization of the batched refill, so the
    subtle draw logic lives exactly once.)"""
    return _refill_empty_slots_batched(
        new[None], is_empty[None], skip[None], points, weights, n_orig, d,
        seed_i[None], acc)[0]


def _refill_empty_slots_batched(new, is_empty, skip, points, weights,
                                n_orig, d, seeds_i, acc):
    """Restart-batched ``_refill_empty_slots``: ``new``/``is_empty``/
    ``skip``/``seeds_i`` carry a leading restart axis R.  Each restart
    draws with ITS OWN key (``seeds_i[r]`` derives from that restart's
    seed, so the batched sweep bit-matches R sequential host fits) and
    consumes its own without-replacement mask.  The loop runs to the MAX
    draw count over restarts — restarts needing fewer draws still compute
    (vmap has no ragged trips) but their mask/centroid updates are gated
    off, so their draw sequences stay exact.

    When the empties outnumber the remaining positive-weight rows, the
    exhausted draws score ``-inf`` everywhere and are NOT installed — the
    slot keeps its old centroid, the host path's under-return rule
    (kmeans_spark.py:201-204, kmeans.py._handle_empty); the host device
    engine caps its draw count the same way."""
    data_shards = axis_size(DATA_AXIS)
    d_idx = lax.axis_index(DATA_AXIS)
    n_glob = n_orig * data_shards
    R = new.shape[0]
    keys = jax.vmap(jax.random.PRNGKey)(seeds_i)
    n_draw = jnp.maximum(jnp.sum(is_empty.astype(jnp.int32), axis=1)
                         - skip, 0)                               # (R,)
    rank = jnp.cumsum(is_empty.astype(jnp.int32), axis=1) - 1

    # One Gumbel array per restart for ALL its draws (the one-shot
    # top-k protocol); each shard slices its local segment once.  Gated
    # under the same no-empties condition as the draw loop, so normal
    # iterations still pay nothing (review r5: hoisted unconditionally,
    # this generated n_glob Gumbels per restart EVERY iteration).
    max_draw = jnp.max(n_draw)
    gs_loc = lax.cond(
        max_draw > 0,
        lambda: jax.vmap(lambda k: lax.dynamic_slice(
            jax.random.gumbel(k, (n_glob,), jnp.float32),
            (d_idx * n_orig,), (n_orig,)))(keys),
        lambda: jnp.zeros((R, n_orig), jnp.float32))         # (R, n_orig)

    def body(i, carry):
        new_c, mask = carry                                  # (R, n_orig)

        def one(g_loc, mask_r):
            score = jnp.where(mask_r > 0, g_loc, -jnp.inf)
            j = jnp.argmax(score)
            return score[j], j

        ss, js = jax.vmap(one)(gs_loc, mask)                 # (R,), (R,)
        rows_l = points[js, :d].astype(acc)                  # (R, d)
        ss_g = lax.all_gather(ss, DATA_AXIS)                 # (S, R)
        js_g = lax.all_gather(js, DATA_AXIS)
        rows_g = lax.all_gather(rows_l, DATA_AXIS)           # (S, R, d)
        win = jnp.argmax(ss_g, axis=0)                       # (R,)
        rows = jnp.take_along_axis(rows_g, win[None, :, None],
                                   axis=0)[0]                # (R, d)
        # A -inf best score means the positive-weight rows are exhausted:
        # no row is installed (the slot keeps its old centroid) and no
        # mask entry is zeroed — matching the host engine's capped draws.
        live = (i < n_draw) & (jnp.max(ss_g, axis=0) > -jnp.inf)
        zero_at = jnp.where((win == d_idx) & live,
                            jnp.take_along_axis(js_g, win[None, :],
                                                axis=0)[0], n_orig)
        mask = jax.vmap(
            lambda m, j: m.at[j].set(0.0, mode="drop"))(mask, zero_at)
        slots = jax.vmap(lambda rk, e, sr: jnp.argmax((rk == sr) & e))(
            rank, is_empty, skip + i)
        new_c = jax.vmap(
            lambda nr, s, rw, a: nr.at[s].set(jnp.where(a, rw, nr[s])))(
                new_c, slots, rows, live)
        return new_c, mask

    w0 = jnp.broadcast_to(weights[:n_orig].astype(jnp.float32),
                          (R, n_orig))
    new, _ = lax.fori_loop(0, max_draw, body, (new, w0))
    return new


def _project_centroids(new, prev, real_mask, project: Optional[str], acc):
    """Device-expressible subclass postprocess hook of the one-dispatch
    fit loops (applied after the mean update + empty refill, before the
    shift test — the same slot as ``KMeans._postprocess_centroids``).

    ``'sphere'`` is SphericalKMeans' hook: re-project each REAL centroid
    row onto the unit sphere (mean direction = normalized mean); a
    zero-norm mean (perfectly cancelling members) keeps the previous
    direction, exactly like the host hook (models/spherical.py).
    Sentinel padding rows must stay sentinel — normalizing one would turn
    it into a valid-looking unit row that could win assignments.
    ``real_mask`` broadcasts over any leading restart axis."""
    if project is None:
        return new
    if project != "sphere":
        raise ValueError(f"unknown device projection {project!r}")
    norm = jnp.sqrt(jnp.sum(new * new, axis=-1, keepdims=True))
    unit = new / jnp.maximum(norm, jnp.finfo(acc).tiny)
    real_c = real_mask[..., None]
    return jnp.where(real_c & (norm > 0), unit,
                     jnp.where(real_c, prev, new))


@_obs_trace.traced_builder
def make_fit_fn(mesh: Mesh, *, chunk_size: int, mode: str = "matmul",
                k_real: int, max_iter: int, tolerance: float,
                empty_policy: str = "keep", history_sse: bool = True,
                project: Optional[str] = None, pipeline: int = 0):
    """Build a FULLY ON-DEVICE training loop: one dispatch runs all
    iterations under ``lax.while_loop``.

    The reference's driver round-trips to the cluster 2-3 times per iteration
    (broadcast/collect/sample, SURVEY.md §3.1); the host-loop ``KMeans.fit``
    already collapses that to one dispatch per iteration; this collapses the
    WHOLE fit to one dispatch — no per-iteration host sync at all, which
    matters when dispatch latency is comparable to compute (remote/tunneled
    chips, small problems).  Trade-offs (mirroring the reference's own
    ``compute_sse`` speed/observability toggle, kmeans_spark.py:34):

    * no per-iteration host logging (the SSE/shift history is returned as
      fixed-size arrays instead);
    * centroid division happens in the accumulation dtype on device (the
      host loop divides in float64);
    * empty-cluster policy: 'keep' (retain old centroid, the reference's
      fallback :201-204), 'farthest' (refill the first empty slot with the
      fused farthest point, the :84-129 policy, then sample rows for any
      REMAINING empties — mirroring the host path), or 'resample' (refill
      EVERY empty slot with seeded uniform positive-weight rows drawn ON
      DEVICE, r1 VERDICT #6).  All empties are refilled in the SAME
      iteration (r2 VERDICT weak #3; the reference samples all
      replacements at once, kmeans_spark.py:196-200), and the draw
      sequence bit-matches the host loop's device sampling engine (see
      ``_refill_empty_slots``), so host- and device-loop trajectories
      agree whenever the host path uses that engine (hostless datasets).

    Returns ``fit(points, weights, centroids0, empty_seeds) ->
    (centroids, n_iters, sse_history[max_iter], shift_history[max_iter],
    counts)`` with everything replicated.  ``empty_seeds`` is the
    (max_iter,) uint32 per-iteration draw-seed schedule
    (``_empty_seed_array(seed, iter0, max_iter)``; any array for
    'keep') — a traced ARGUMENT, not a baked constant, so fits that
    differ only by seed (restarts, bisecting splits, resumes) share one
    compiled program.

    ``pipeline`` selects the chunk schedule of the statistics pass
    (``_local_stats``; bit-exact parity partners).  Under the guarded
    bf16 rung (``mode='matmul_bf16_guarded'``, ISSUE 8) the return
    gains ONE trailing replicated int32 — the total bf16-guard-corrected
    row count over all iterations and shards (the per-fit audit the
    model publishes as ``bf16_guard_corrected_rows_``); the rung is
    rejected under TP sharding and with the 'farthest' policy
    (``_check_guarded``).
    """
    if empty_policy not in ("keep", "farthest", "resample"):
        raise ValueError(
            f"on-device loop supports empty_cluster 'keep', 'farthest' or "
            f"'resample', got {empty_policy!r}")
    data_shards, model_shards = mesh_shape(mesh)
    _check_guarded(mode, model_shards, empty_policy)
    guarded = (mode == GUARDED_MODE)
    # Elide unneeded per-iteration statistics (the reference's own
    # compute_sse speed/observability trade, kmeans_spark.py:34): skipping
    # the SSE/min-distance reductions and farthest tracking saves real VPU
    # time per (chunk, k) tile when the caller doesn't consume them.
    need_sse = bool(history_sse)
    need_farthest = (empty_policy == "farthest")

    def fit(points, weights, centroids_block, empty_seeds):
        if empty_seeds.shape != (max_iter,):
            raise ValueError(f"empty_seeds must have shape ({max_iter},) "
                             f"(one per iteration), got "
                             f"{empty_seeds.shape}")
        k_local, d = centroids_block.shape
        acc = _accum_dtype(points.dtype)
        # The empty-slot refill draws against the PRE-prep row space so it
        # bit-matches the host engine (whose gumbel runs over the dataset's
        # padded global shape); only the small (n,) weight vector is kept
        # alive past prep_points — rows are gathered from the prepped
        # array, whose leading n_orig rows are unchanged.
        n_orig, w_draw = points.shape[0], weights
        x2w = w_col = None
        if mode in PALLAS_MODES:
            # Hoist the kernel's x-side padding/fold-column/weight-layout
            # prep out of the iteration loop (~3 + 1.6 ms/iter at the
            # benchmark shapes; XLA does not hoist the full-array work
            # itself), and precompute the loop-invariant SSE term (see
            # _sse_from_stats; single-block stats only — the TP path's
            # SSE comes from the gathered global minima).
            from kmeans_tpu.ops.pallas_kernels import prep_points
            if need_sse and model_shards <= 1:
                x2w = _weighted_sqnorm_total(points, weights)
            points, weights, w_col = prep_points(points, weights)
        k_pad = k_local * model_shards
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        real = jnp.arange(k_pad) < k_real          # mask off sentinel rows

        def global_stats(cents_block):
            st, corr = _local_stats(points, weights, cents_block,
                                    chunk_size=chunk_size, mode=mode,
                                    model_shards=model_shards,
                                    need_sse=need_sse,
                                    need_farthest=need_farthest,
                                    need_sse_pc=False, x2w=x2w,
                                    w_col=w_col, pipeline=pipeline,
                                    real_mask=real if guarded else None)
            if guarded:
                corr = lax.psum(corr, (DATA_AXIS, MODEL_AXIS))
            off = jnp.asarray(m_idx * k_local, jnp.int32)
            sums = lax.psum(lax.dynamic_update_slice(
                jnp.zeros((k_pad, d), acc), st.sums, (off, jnp.int32(0))),
                (DATA_AXIS, MODEL_AXIS))
            counts = lax.psum(lax.dynamic_update_slice(
                jnp.zeros((k_pad,), acc), st.counts, (off,)),
                (DATA_AXIS, MODEL_AXIS))
            sse = (lax.psum(st.sse, (DATA_AXIS, MODEL_AXIS)) / model_shards
                   if need_sse else st.sse)
            if need_farthest:
                far_ds = lax.all_gather(st.farthest_dist,
                                        (DATA_AXIS, MODEL_AXIS))
                far_ps = lax.all_gather(st.farthest_point,
                                        (DATA_AXIS, MODEL_AXIS))
                j = jnp.argmax(far_ds)
                far_d, far_p = far_ds[j], far_ps[j]
            else:
                far_d, far_p = st.farthest_dist, st.farthest_point
            return sums, counts, sse, far_d, far_p, corr

        def body(state):
            i, cents_full, _, sse_hist, shift_hist, _, _, corr_tot = state
            cents_block = lax.dynamic_slice(
                cents_full, (jnp.asarray(m_idx * k_local, jnp.int32),
                             jnp.int32(0)), (k_local, d))
            sums, counts, sse, far_d, far_p, corr = \
                global_stats(cents_block)
            mean = sums / jnp.maximum(counts, 1.0)[:, None]
            new = jnp.where((counts > 0)[:, None], mean.astype(acc),
                            cents_full)
            if empty_policy == "farthest":
                # Host-path semantics (kmeans.py._handle_empty): the
                # farthest point takes the FIRST empty slot (only when its
                # distance is valid), every remaining empty gets a drawn
                # row in the same iteration.
                is_empty = (counts <= 0) & real
                first_empty = jnp.argmax(is_empty)
                use_far = jnp.any(is_empty) & (far_d >= 0)
                refill = jnp.where(use_far, far_p[:d].astype(acc),
                                   new[first_empty])
                new = new.at[first_empty].set(refill)
                new = _refill_empty_slots(
                    new, is_empty, use_far.astype(jnp.int32), points,
                    w_draw, n_orig, d, empty_seeds[i], acc)
            elif empty_policy == "resample":
                is_empty = (counts <= 0) & real
                new = _refill_empty_slots(
                    new, is_empty, jnp.int32(0), points, w_draw, n_orig,
                    d, empty_seeds[i], acc)
            new = _project_centroids(new, cents_full, real, project, acc)
            shifts = jnp.sqrt(jnp.sum((new - cents_full) ** 2, axis=1))
            max_shift = jnp.max(jnp.where(real, shifts, 0.0))
            sse_hist = sse_hist.at[i].set(sse)
            shift_hist = shift_hist.at[i].set(max_shift)
            # All-finite flag (ISSUE 5): a blown-up table stops the loop
            # at the DIVERGING iteration (i+1 after the increment below)
            # instead of spinning NaNs to max_iter — the host maps the
            # early exit to a NumericalDivergenceError naming it.  For
            # healthy fits the flag is constant-true: the arithmetic of
            # every iteration is untouched (parity oracles unaffected).
            ok = jnp.all(jnp.isfinite(jnp.where(real[:, None], new, 0.0)))
            return (i + 1, new, max_shift, sse_hist, shift_hist, counts,
                    ok, corr_tot + corr)

        def cond(state):
            i, _, max_shift, _, _, _, ok, _ = state
            return (i < max_iter) & ((i == 0) | (max_shift >= tolerance)) \
                & ok

        cents0 = lax.all_gather(centroids_block, MODEL_AXIS,
                                tiled=True).astype(acc) \
            if model_shards > 1 else centroids_block.astype(acc)
        state = (jnp.int32(0), cents0, jnp.asarray(jnp.inf, acc),
                 jnp.zeros((max_iter,), acc), jnp.zeros((max_iter,), acc),
                 jnp.zeros((k_pad,), acc), jnp.asarray(True),
                 jnp.zeros((), jnp.int32))
        i, cents, _, sse_hist, shift_hist, counts, _, corr_tot = \
            lax.while_loop(cond, body, state)
        out = (cents[:k_real], i, sse_hist, shift_hist, counts[:k_real])
        return out + (corr_tot,) if guarded else out

    out_specs = (P(None, None), P(), P(), P(), P(None))
    if guarded:
        out_specs = out_specs + (P(),)
    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None),
                  P(None)),
        out_specs=out_specs,
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_multi_fit_fn(mesh: Mesh, *, chunk_size: int, mode: str = "matmul",
                      k_real: int, max_iter: int, tolerance: float,
                      empty_policy: str = "keep", n_init: int,
                      history_sse: bool = True,
                      project: Optional[str] = None,
                      k_reals=None, return_all: bool = False,
                      pipeline: int = 0, member_points: bool = False):
    """Build a BATCHED on-device training loop: ``n_init`` independent
    restarts run in ONE dispatch, vmapped over the restart axis.

    This is the TPU-native answer to sklearn's ``n_init`` (the reference has
    no restarts at all — one Forgy draw, kmeans_spark.py:58-82): instead of
    R sequential fits, the restart axis becomes a batch dimension of every
    kernel — the (chunk, k) distance matmul turns into (R, chunk, k), which
    *raises* MXU utilization for small k, and the whole sweep still costs a
    single dispatch.  Restarts converge independently: a converged restart is
    frozen (its centroids stop moving, its stats stop being recorded) while
    the ``lax.while_loop`` keeps running until every restart is done or
    ``max_iter`` is hit.

    Selection: after the loop, ONE extra vmapped pass scores every restart's
    FINAL centroids (true final inertia — sklearn's selection rule; the
    in-loop SSE history lags one iteration by reference semantics,
    kmeans_spark.py:279) and the argmin restart wins.

    ``model``-axis (centroid-table) sharding composes with the restart
    batch (r1 VERDICT #3): blocks arrive (R, k_local, D) sharded on axis 1,
    each shard scores points against its block only, and the loop carries
    the gathered full table per restart.  ``empty_policy`` may be any of
    'keep' / 'farthest' / 'resample'; ALL empty slots refill in the same
    iteration, and each restart's draws are keyed by ITS row of the
    ``empty_seeds`` (R, max_iter) argument (per-restart
    ``_empty_seed_array`` rows — the same seeds the host-sequential path
    feeds ``_handle_empty``), so the batched sweep refills exactly like R
    sequential fits while every seed set shares one compiled program.

    ``k_reals`` generalizes the member axis from restarts to a MODEL-
    SELECTION sweep (ISSUE 7): a length-``n_init`` sequence of per-member
    real cluster counts (each <= ``k_real``, which stays the pad target
    k_max).  Member r's rows ``k_reals[r]..k_pad`` must arrive as INERT
    sentinel centroids (``PAD_CENTROID_VALUE`` rows — the same padding
    discipline the model axis already uses): sentinels never win an
    assignment, so their counts stay zero, they keep their sentinel value
    through the mean update, are excluded from the empty-refill /
    projection / shift masks by the per-member ``real`` mask, and every
    real row's arithmetic is untouched — a member padded k_m -> k_max is
    bit-identical to its standalone k_m fit wherever the dots are exact
    (the r10 parity-class table; each distance column and each one-hot
    scatter row is an independent dot product, and min/argmin over extra
    sentinel columns is exact).  ``k_reals=None`` keeps the homogeneous
    restart behavior exactly.

    Returns ``fit(points, weights, centroids0[R,k,D],
    empty_seeds[R,max_iter]) -> (best_centroids,
    n_iters_best, sse_hist_best, shift_hist_best, counts_best, best_idx,
    final_inertias[R])`` with everything replicated.  ``return_all=True``
    returns instead the PER-MEMBER states the sweep engine selects from on
    the host: ``(centroids[R,k_real,D], n_iters[R], sse_hist[R,max_iter],
    shift_hist[R,max_iter], counts[R,k_real], final_inertias[R])``.

    ``member_points=True`` generalizes the member axis once more (ISSUE
    16, the batched PQ codebook trainer): ``points`` arrives with a
    LEADING member axis — (R, n_local, d) sharded on the data axis at
    axis 1 — and member r trains against ITS OWN rows (the r-th
    subspace's column slice) instead of a shared dataset.  Everything
    else about the member axis is unchanged, so one dispatch trains all
    R subspace codebooks.  Restricted to the matmul-class modes and
    ``empty_policy='keep'``: the Gumbel refill engine draws rows from
    the SHARED dataset by global index, which has no per-member-rows
    form (a PQ subspace with an empty code keeps its old codeword — the
    sklearn-encoder behavior), and the Pallas prep hoists are
    shared-points programs.

    ``pipeline`` selects the chunk schedule (``_local_stats``).  Under
    the guarded bf16 rung the member passes run under ``lax.map``
    instead of ``vmap`` (a vmapped ``lax.cond`` lowers to a select that
    executes BOTH branches, which would pay the f32 correction tile for
    every chunk of every member; ``lax.map`` keeps the cond real — the
    Pallas-mode precedent, and at the guarded rung's target shapes a
    single member already saturates the MXU) and the return gains one
    trailing replicated int32: the total corrected-row count over all
    members/iterations (in BOTH return shapes).
    """
    if empty_policy not in ("keep", "farthest", "resample"):
        raise ValueError(
            f"on-device loop supports empty_cluster 'keep', 'farthest' or "
            f"'resample', got {empty_policy!r}")
    _check_guarded(mode, mesh_shape(mesh)[1], empty_policy)
    guarded = (mode == GUARDED_MODE)
    if member_points:
        if mode in PALLAS_MODES or guarded:
            raise ValueError(
                f"member_points supports the matmul-class modes only, "
                f"got {mode!r} (the Pallas prep hoists and the guarded "
                "rung are shared-points programs)")
        if empty_policy != "keep":
            raise ValueError(
                f"member_points requires empty_cluster='keep', got "
                f"{empty_policy!r}: the Gumbel refill engine draws rows "
                "from the shared dataset by global index, which has no "
                "per-member-rows form")
    if k_reals is not None:
        k_reals = np.asarray(k_reals, np.int32)
        if k_reals.shape != (n_init,):
            raise ValueError(f"k_reals must have shape ({n_init},), got "
                             f"{k_reals.shape}")
        if np.any(k_reals < 1) or np.any(k_reals > k_real):
            raise ValueError(f"k_reals entries must be in [1, {k_real}], "
                             f"got {k_reals.tolist()}")
    data_shards, model_shards = mesh_shape(mesh)

    def fit(points, weights, cents0_blocks, empty_seeds):
        # cents0_blocks: (R, k_local, d), k axis sharded on MODEL.
        # points: (n_local, d) shared, or (R, n_local, d) per-member.
        acc = _accum_dtype(points.dtype)
        R, k_local, d = cents0_blocks.shape
        if empty_seeds.shape != (R, max_iter):
            raise ValueError(f"empty_seeds must have shape ({R}, "
                             f"{max_iter}) (one row per restart), got "
                             f"{empty_seeds.shape}")
        n_orig = points.shape[1] if member_points else points.shape[0]
        w_draw = weights                            # pre-prep row space
        x2w = w_col = None
        if mode in PALLAS_MODES:
            # Hoist the kernel's x-side prep out of the loop (see
            # make_fit_fn); shared by every restart.
            from kmeans_tpu.ops.pallas_kernels import prep_points
            if model_shards <= 1:
                x2w = _weighted_sqnorm_total(points, weights)
            points, weights, w_col = prep_points(points, weights)
        k_pad = k_local * model_shards
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        # Per-member real-row mask (R, k_pad): sentinel rows — the model-
        # axis padding AND, under a k sweep, each member's inert rows
        # beyond its own k — are masked out of the empty-refill /
        # projection / shift tests.  Homogeneous restarts broadcast one
        # row, so the compiled arithmetic is unchanged.
        ks = (np.full((n_init,), k_real, np.int32) if k_reals is None
              else k_reals)
        real = jnp.asarray(np.arange(k_pad)[None, :] < ks[:, None])
        axes = (DATA_AXIS, MODEL_AXIS)

        need_farthest = (empty_policy == "farthest")

        def all_stats(cents, need_sse):
            """Global per-restart stats: vmap the shard-local pass over R
            (collectives vectorize over the restart batch), slicing each
            restart's centroid block from its full table, then psum the
            embedded accumulators over both mesh axes.  Optional
            statistics are elided per the need flags."""
            def local(c_full, r_mask, pts):
                blk = lax.dynamic_slice(
                    c_full, (jnp.asarray(m_idx * k_local, jnp.int32),
                             jnp.int32(0)), (k_local, d))
                # Guarded rung: the MEMBER's real-row mask keeps its
                # inert sentinel rows (k-sweep padding, 1e12 norms) out
                # of the guard's distance scale (model_shards == 1 under
                # the rung, so the block IS the full k_pad table).
                return _local_stats(pts, weights,
                                    blk.astype(pts.dtype),
                                    chunk_size=chunk_size, mode=mode,
                                    model_shards=model_shards,
                                    need_sse=need_sse,
                                    need_farthest=need_farthest,
                                    need_sse_pc=False, x2w=x2w,
                                    w_col=w_col, pipeline=pipeline,
                                    real_mask=r_mask if guarded else None)
            if member_points:
                # Per-member rows batch alongside the member's centroid
                # table (ISSUE 16: one dispatch trains all subspace
                # codebooks).
                st, corrs = jax.vmap(local)(cents, real, points)
            elif mode in PALLAS_MODES or guarded:
                # vmapping a pallas_call over the restart axis
                # MATERIALIZES the unbatched points operand R times
                # (r5, found by the 10M x R=4 time-to-solution run:
                # a 4 x 5.1 GB broadcast OOMed the 16 GB chip).  The
                # restarts run sequentially inside the same dispatch
                # instead — at pallas shapes (k >= 512) a single
                # restart already saturates the MXU, so the batching
                # win the vmap bought at small k does not exist here.
                # The guarded rung rides the same path: vmap would turn
                # its per-chunk correction cond into a both-branches
                # select (see the builder docstring).
                st, corrs = lax.map(lambda a: local(*a, points),
                                    (cents, real))
            else:
                st, corrs = jax.vmap(local, in_axes=(0, 0, None))(
                    cents, real, points)
            off = jnp.asarray(m_idx * k_local, jnp.int32)
            sums = lax.psum(jax.vmap(lambda s: lax.dynamic_update_slice(
                jnp.zeros((k_pad, d), acc), s.astype(acc),
                (off, jnp.int32(0))))(st.sums), axes)      # (R, k_pad, d)
            counts = lax.psum(jax.vmap(lambda c: lax.dynamic_update_slice(
                jnp.zeros((k_pad,), acc), c.astype(acc), (off,)))(
                    st.counts), axes)                      # (R, k_pad)
            sse = (lax.psum(st.sse, axes) / model_shards
                   if need_sse else st.sse)
            if need_farthest:
                far_ds = lax.all_gather(st.farthest_dist, axes)
                far_ps = lax.all_gather(st.farthest_point, axes)
                owner = jnp.argmax(far_ds, axis=0)         # (R,)
                far_d = jnp.max(far_ds, axis=0)            # (R,)
                far_p = jnp.take_along_axis(
                    far_ps, owner[None, :, None], axis=0)[0]   # (R, d)
            else:
                far_d, far_p = st.farthest_dist, st.farthest_point
            corr = (lax.psum(jnp.sum(corrs, dtype=jnp.int32), axes)
                    if guarded else jnp.zeros((), jnp.int32))
            return sums, counts, sse, far_d, far_p, corr

        def body(state):
            (i, cents, done, n_iters, sse_hist, shift_hist, counts_out,
             corr_tot) = state
            sums, counts, sse, far_d, far_p, corr = all_stats(
                cents, history_sse)
            mean = sums / jnp.maximum(counts, 1.0)[..., None]
            new = jnp.where((counts > 0)[..., None], mean.astype(acc), cents)
            if empty_policy == "farthest":
                # Host-path semantics per restart: farthest point fills
                # the first empty, drawn rows fill the rest (same iter).
                is_empty = (counts <= 0) & real            # (R, k_pad)
                use_far = jnp.any(is_empty, axis=1) & (far_d >= 0)

                def refill(new_r, far_r, emp_r, use_r):
                    fe = jnp.argmax(emp_r)
                    val = jnp.where(use_r, far_r[:d].astype(acc),
                                    new_r[fe])
                    return new_r.at[fe].set(val)
                new = jax.vmap(refill)(new, far_p, is_empty, use_far)
                new = _refill_empty_slots_batched(
                    new, is_empty, use_far.astype(jnp.int32), points,
                    w_draw, n_orig, d, empty_seeds[:, i], acc)
            elif empty_policy == "resample":
                is_empty = (counts <= 0) & real
                new = _refill_empty_slots_batched(
                    new, is_empty, jnp.zeros((R,), jnp.int32), points,
                    w_draw, n_orig, d, empty_seeds[:, i], acc)
            new = _project_centroids(new, cents, real, project, acc)
            shifts = jnp.sqrt(jnp.sum((new - cents) ** 2, axis=2))
            max_shift = jnp.max(jnp.where(real, shifts, 0.0),
                                axis=1)                    # (R,)
            # Frozen restarts keep their centroids and recorded stats.
            new = jnp.where(done[:, None, None], cents, new)
            sse_hist = sse_hist.at[:, i].set(jnp.where(done, 0.0, sse))
            shift_hist = shift_hist.at[:, i].set(
                jnp.where(done, 0.0, max_shift))
            counts_out = jnp.where(done[:, None], counts_out, counts)
            n_iters = jnp.where(done, n_iters, i + 1)
            done = done | (max_shift < tolerance)
            return (i + 1, new, done, n_iters, sse_hist, shift_hist,
                    counts_out, corr_tot + corr)

        def cond(state):
            i, _, done, *_ = state
            return (i < max_iter) & ~jnp.all(done)

        cents0 = lax.all_gather(cents0_blocks, MODEL_AXIS, axis=1,
                                tiled=True).astype(acc) \
            if model_shards > 1 else cents0_blocks.astype(acc)
        state = (jnp.int32(0), cents0,
                 jnp.zeros((R,), bool), jnp.zeros((R,), jnp.int32),
                 jnp.zeros((R, max_iter), acc), jnp.zeros((R, max_iter), acc),
                 jnp.zeros((R, k_pad), acc), jnp.zeros((), jnp.int32))
        _, cents, _, n_iters, sse_hist, shift_hist, counts_out, corr_tot = \
            lax.while_loop(cond, body, state)

        # Selection pass: true final inertia of each restart's centroids
        # (SSE always computed here — it IS the selection criterion).
        # Its guard-corrected count is NOT added to the audit: the
        # attribute means training-loop flags per iteration on every
        # path (make_fit_fn counts loop iterations only), and a rate
        # derived as corrected/(iterations*n) must not be inflated by
        # the one extra scoring pass.
        _, _, final_sse, _, _, _ = all_stats(cents, True)
        if return_all:
            # Sweep mode: selection happens on the HOST (the criterion may
            # be a batched metric, not inertia) — hand back every member's
            # final state, trimmed to the pad target k_real; each member's
            # own trim to k_reals[r] is the caller's.
            out = (cents[:, :k_real], n_iters, sse_hist, shift_hist,
                   counts_out[:, :k_real], final_sse)
        else:
            best = jnp.argmin(final_sse)
            out = (cents[best, :k_real], n_iters[best], sse_hist[best],
                   shift_hist[best], counts_out[best, :k_real], best,
                   final_sse)
        return out + (corr_tot,) if guarded else out

    out_specs = ((P(None, None, None), P(None), P(None, None),
                  P(None, None), P(None, None), P(None)) if return_all
                 else (P(None, None), P(), P(None), P(None), P(None), P(),
                       P(None)))
    if guarded:
        out_specs = out_specs + (P(),)
    points_spec = (P(None, DATA_AXIS, None) if member_points
                   else P(DATA_AXIS, None))
    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(points_spec, P(DATA_AXIS),
                  P(None, MODEL_AXIS, None), P(None, None)),
        out_specs=out_specs,
        check_vma=False)
    if mode in PALLAS_MODES:
        # The lax.map-wrapped kernel call sits inside a fusion whose
        # per-restart carries push XLA's default 16 MB scoped-vmem pool
        # ~2% over (observed at 10M x R=4 on v5e); the kernel itself
        # budgets against the separate 100 MB pltpu VMEM limit, so
        # doubling the scoped pool for THIS program is safe headroom,
        # not a tuning change.
        return jax.jit(mapped, compiler_options={
            "xla_tpu_scoped_vmem_limit_kib": 32768})
    return jax.jit(mapped)


def _check_minibatch_mode(mode: str) -> None:
    """The Sculley engines keep the f32-class modes: a mini-batch's
    statistics pass is ONE chunk (batch_per_shard == chunk), so there is
    no bf16-rate matmul big enough to guard — and the update itself is a
    sampled approximation, where a bit-exactness rung has nothing to
    protect.  Pointed rejection so the knob fails loudly, mirroring the
    TP rejection (``_check_guarded``)."""
    if mode == GUARDED_MODE:
        raise ValueError(
            "distance_mode='matmul_bf16_guarded' applies to the "
            "full-batch Lloyd engines (KMeans/SphericalKMeans fit "
            "paths); the mini-batch Sculley engines run the f32-class "
            "modes — use 'matmul' (exact) or 'matmul_bf16' (unguarded)")


@_obs_trace.traced_builder
def make_minibatch_step_fn(mesh: Mesh, *, batch_per_shard: int,
                           mode: str = "matmul",
                           n_candidates: int = 0,
                           pipeline: int = 0) -> Callable:
    """Build the fused ON-DEVICE mini-batch iteration:
    (points, weights, centroids, key) -> StepStats of a freshly-sampled
    batch — sampling AND statistics in ONE dispatch.

    Replaces the r1 host path (per-iteration ``rng.choice`` + full batch
    re-upload, r1 VERDICT #4): each data shard draws ``batch_per_shard``
    of its own resident rows, gathers them shard-locally — no cross-shard
    traffic — and feeds them through the same ``_local_stats`` pass as the
    full-batch step.  On a tunneled chip this removes the per-iteration
    batch upload that made the host path transfer-bound.

    Sampling: STRATIFIED without replacement, O(batch) — the shard's
    ``n_local`` rows are split into ``batch_per_shard`` contiguous strata,
    one uniform row is drawn per stratum, and a per-iteration uniform
    rotation of the whole index space makes every row reachable across
    iterations (without it, the ``n_local mod batch`` tail rows would
    never be sampled).  A Gumbel top-k draw (exact uniform w/o
    replacement, as in ``models.init._kmeanspp_device``) was measured
    first and REJECTED: its sort over the full shard cost ~330 ms/iter at
    N=2M on a v5e — more than 100x the batch's actual compute.  Each
    point's marginal inclusion probability remains uniform; the joint
    constraint (one row per rotated stratum) is harmless for Sculley
    updates (sklearn's MiniBatchKMeans samples WITH replacement, an even
    weaker guarantee).  Zero-weight (padding) rows can be selected but
    carry weight 0 into every statistic.  The draw is a pure function of
    (key, shard index) and is replicated across the model axis (the key
    folds in the DATA index only, so model replicas gather identical
    rows).

    Returned stats are replicated like ``make_step_fn``'s (sums, counts,
    sse over the batch; farthest/per-cluster elided — the Sculley update
    uses none of them).  ``n_candidates > 0`` additionally returns
    ``n_candidates`` uniformly-drawn rows of the batch (plus a validity
    mask) for the host-side low-count reassignment decision
    (``_batch_candidates``); the return type becomes
    (stats, cand_rows, cand_valid).

    ``pipeline`` is accepted for knob-surface symmetry with the Lloyd
    builders but DEGENERATES to the serial body: the batch is exactly
    one scan chunk (``chunk_size == batch_per_shard``), and a
    single-chunk pipelined schedule IS the serial schedule
    (``_local_stats``).
    """
    data_shards, model_shards = mesh_shape(mesh)
    _check_minibatch_mode(mode)

    def step(points, weights, centroids_block, key, iteration):
        k_local, d = centroids_block.shape
        acc = _accum_dtype(points.dtype)
        base_i = jax.random.fold_in(key, iteration)
        bx, bw = _sample_batch(points, weights, base_i,
                               batch_per_shard, data_shards)
        st, _ = _local_stats(bx, bw, centroids_block,
                             chunk_size=batch_per_shard, mode=mode,
                             model_shards=model_shards, need_sse=True,
                             need_farthest=False, need_sse_pc=False,
                             pipeline=pipeline)
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        k = k_local * model_shards
        off = jnp.asarray(m_idx * k_local, jnp.int32)
        axes = (DATA_AXIS, MODEL_AXIS)
        sums = lax.psum(lax.dynamic_update_slice(
            jnp.zeros((k, d), st.sums.dtype), st.sums,
            (off, jnp.int32(0))), axes)
        counts = lax.psum(lax.dynamic_update_slice(
            jnp.zeros((k,), st.counts.dtype), st.counts, (off,)), axes)
        sse = lax.psum(st.sse, axes) / model_shards
        zero = jnp.zeros((), acc)
        stats = StepStats(sums, counts, sse, zero,
                          jnp.zeros((d,), acc), jnp.zeros((k,), acc))
        if n_candidates <= 0:
            return stats
        cand_rows, cand_valid = _batch_candidates(
            bx, bw, base_i, n_candidates, data_shards)
        return stats, cand_rows, cand_valid

    stats_spec = StepStats(P(None, None), P(None), P(), P(), P(None),
                           P(None))
    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None),
                  P(None), P()),
        out_specs=stats_spec if n_candidates <= 0
        else (stats_spec, P(None, None), P(None)),
        check_vma=False)
    return jax.jit(mapped)


def _sample_batch(points, weights, key, batch_per_shard: int,
                  data_shards: int):
    """Shard-local stratified batch draw (see make_minibatch_step_fn's
    docstring for the design rationale and the rejected Gumbel top-k
    alternative).  Returns (bx (bs_local, D), bw (bs_local,))."""
    d_idx = lax.axis_index(DATA_AXIS) if data_shards > 1 else 0
    shard_key = jax.random.fold_in(key, d_idx)
    n_local = points.shape[0]
    stratum = n_local // batch_per_shard         # >= 1: caller guarantees
    k_rot, k_row = jax.random.split(shard_key)
    rho = jax.random.randint(k_rot, (), 0, n_local, dtype=jnp.int32)
    r = jax.random.randint(k_row, (batch_per_shard,), 0, stratum,
                           dtype=jnp.int32)
    offs = jnp.arange(batch_per_shard, dtype=jnp.int32) * stratum
    idx = (offs + r + rho) % n_local             # distinct mod-n_local rows
    return points[idx], weights[idx]


def _batch_candidates(bx, bw, base_i, n_cand: int, data_shards: int):
    """Draw up to ``n_cand`` distinct positive-weight rows from the CURRENT
    global mini-batch, uniformly, with the result replicated on every shard:
    a seeded Gumbel-top-k per data shard, then a global top-k over the
    gathered per-shard winners (exact — any global top-``n_cand`` element is
    in its own shard's top ``n_cand``).

    These rows seed sklearn-style low-count center reassignment — the
    mini-batch analogue of the reference's empty-cluster resample
    (kmeans_spark.py:190-204), which draws replacement centers from the
    data when a center stops receiving points.

    Key discipline: ``base_i`` is the iteration's batch key
    (``fold_in(key, iteration)``); each shard folds in
    ``data_shards + d_idx`` — disjoint from the batch draw's
    ``fold_in(base_i, d_idx)`` stream (d_idx < data_shards) and a pure
    function of (seed, iteration, shard), so the per-iteration and
    one-dispatch engines draw bit-identical candidates and resumes
    continue the exact sequence.  Like the batch draw, the key folds in
    the DATA index only, so model-axis replicas agree.

    Returns (rows (n_cand, d), valid (n_cand,) bool) — ``valid`` is False
    for tail slots when the batch has fewer positive rows than ``n_cand``.
    """
    d_idx = lax.axis_index(DATA_AXIS) if data_shards > 1 else 0
    ck = jax.random.fold_in(base_i, data_shards + d_idx)
    bs_local, d = bx.shape
    kc = min(n_cand, bs_local)
    g = jax.random.gumbel(ck, (bs_local,), jnp.float32)
    score = jnp.where(bw > 0, g, -jnp.inf)
    s_loc, idx = lax.top_k(score, kc)
    rows_loc = bx[idx]                                    # (kc, d)
    if data_shards > 1:
        s_all = lax.all_gather(s_loc, DATA_AXIS).reshape(-1)
        rows_all = lax.all_gather(rows_loc, DATA_AXIS).reshape(-1, d)
    else:
        s_all, rows_all = s_loc, rows_loc
    if s_all.shape[0] < n_cand:        # k > global batch: pad with invalid
        pad = n_cand - s_all.shape[0]
        s_all = jnp.concatenate(
            [s_all, jnp.full((pad,), -jnp.inf, s_all.dtype)])
        rows_all = jnp.concatenate(
            [rows_all, jnp.zeros((pad, d), rows_all.dtype)])
    s_best, j = lax.top_k(s_all, n_cand)
    return rows_all[j], s_best > -jnp.inf


def apply_reassignment(new, seen, cand_rows, cand_valid, real, do_re,
                       ratio: float, n_cand: int, acc):
    """sklearn-style low-count center reassignment, shared by the
    mini-batch device loops: centers whose lifetime ``seen`` count fell
    below ``ratio * seen.max()`` are re-seeded from the current batch's
    candidate rows (in slot order), and their counts reset to the minimum
    count among the KEPT centers (sklearn's 'not too small to avoid
    instant reassignment' rule).  ``do_re`` gates the whole step (the
    every-``reassign_every``-iterations cadence); tie-break and ordering
    are deterministic so host- and device-loop trajectories agree.
    Returns (new, seen)."""
    seen_real = jnp.where(real, seen, -jnp.inf)
    thresh = ratio * jnp.max(seen_real)
    flagged = (seen < thresh) & real & do_re
    rank = jnp.cumsum(flagged.astype(jnp.int32)) - 1
    take = jnp.clip(rank, 0, n_cand - 1)
    ok = flagged & (rank < n_cand) & cand_valid[take]
    new = jnp.where(ok[:, None], cand_rows.astype(acc)[take], new)
    keep_min = jnp.min(jnp.where(real & ~flagged, seen, jnp.inf))
    keep_min = jnp.where(jnp.isfinite(keep_min), keep_min, 0.0)
    seen = jnp.where(ok, keep_min, seen)
    return new, seen


@_obs_trace.traced_builder
def make_minibatch_fit_fn(mesh: Mesh, *, batch_per_shard: int,
                          mode: str = "matmul", k_real: int, max_iter: int,
                          tolerance: float, history_sse: bool = True,
                          reassignment_ratio: float = 0.0,
                          reassign_every: int = 1, pipeline: int = 0):
    """Build the FULLY ON-DEVICE mini-batch training loop: ALL iterations
    (sampling + batch stats + Sculley update) in ONE dispatch under
    ``lax.while_loop`` — the mini-batch analogue of ``make_fit_fn``.

    On a tunneled chip the per-iteration path costs ~5 host round trips
    per iteration (key fold, centroid upload, stat transfers) while the
    batch's actual compute is sub-millisecond, so the whole fit is
    dispatch-bound; this removes every per-iteration sync.  Same
    trade-offs as ``make_fit_fn``: no per-iteration host logging
    (histories returned as arrays) and the Sculley interpolation runs in
    the accumulation dtype on device (the host loop interpolates in
    float64).

    ``iter0`` offsets the sampling keys so a resumed fit draws the SAME
    batch sequence an uninterrupted run would (checkpoint continuity);
    ``seen0`` carries the lifetime per-center counts across resumes.

    ``reassignment_ratio > 0`` enables sklearn-style dead-center
    recovery — the mini-batch analogue of the reference's ONE fault path
    (empty-cluster resample, kmeans_spark.py:190-204): every
    ``reassign_every`` GLOBAL iterations, centers whose lifetime count is
    below ``reassignment_ratio * seen.max()`` are re-seeded from rows of
    the current batch (``_batch_candidates`` — same key schedule as the
    per-iteration engine, so the two trajectories agree) and their counts
    reset to the kept centers' minimum.  The cadence and draws key off
    the ABSOLUTE iteration (``iter0 + i``), preserving resume continuity.

    Returns ``fit(points, weights, centroids0, key, iter0, seen0) ->
    (centroids, seen, n_iters, sse_hist[max_iter], shift_hist[max_iter],
    counts_last)`` with everything replicated.  ``sse_hist`` entries are
    scaled batch estimates (total weight / batch weight), matching the
    host path.  ``pipeline`` degenerates to serial here (single-chunk
    batch pass — see ``make_minibatch_step_fn``); the guarded bf16 rung
    is rejected (``_check_minibatch_mode``).
    """
    data_shards, model_shards = mesh_shape(mesh)
    _check_minibatch_mode(mode)

    def fit(points, weights, cents_block, key, iter0, seen0):
        k_local, d = cents_block.shape
        acc = _accum_dtype(points.dtype)
        k_pad = k_local * model_shards
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        real = jnp.arange(k_pad) < k_real
        axes = (DATA_AXIS, MODEL_AXIS)
        w_total = lax.psum(jnp.sum(weights.astype(acc)),
                           axes) / model_shards

        def batch_stats(cents_full, i):
            blk = lax.dynamic_slice(
                cents_full, (jnp.asarray(m_idx * k_local, jnp.int32),
                             jnp.int32(0)), (k_local, d))
            base_i = jax.random.fold_in(key, iter0 + i)
            bx, bw = _sample_batch(points, weights, base_i,
                                   batch_per_shard, data_shards)
            st, _ = _local_stats(bx, bw, blk.astype(points.dtype),
                                 chunk_size=batch_per_shard, mode=mode,
                                 model_shards=model_shards,
                                 need_sse=history_sse,
                                 need_farthest=False,
                                 need_sse_pc=False, pipeline=pipeline)
            off = jnp.asarray(m_idx * k_local, jnp.int32)
            sums = lax.psum(lax.dynamic_update_slice(
                jnp.zeros((k_pad, d), acc), st.sums,
                (off, jnp.int32(0))), axes)
            counts = lax.psum(lax.dynamic_update_slice(
                jnp.zeros((k_pad,), acc), st.counts, (off,)), axes)
            sse = (lax.psum(st.sse, axes) / model_shards
                   if history_sse else st.sse)
            if reassignment_ratio <= 0:
                cand = None
            elif reassign_every == 1:
                cand = _batch_candidates(bx, bw, base_i, k_real,
                                         data_shards)
            else:
                # Off-cadence iterations skip the draw (Gumbel + top_k +
                # (k, D) all_gather) entirely; the predicate is shard-
                # uniform (replicated loop counter), so the collective
                # inside the cond is safe.
                cand = lax.cond(
                    ((iter0 + i + 1) % reassign_every) == 0,
                    lambda: _batch_candidates(bx, bw, base_i, k_real,
                                              data_shards),
                    lambda: (jnp.zeros((k_real, d), bx.dtype),
                             jnp.zeros((k_real,), bool)))
            return sums, counts, sse, cand

        def body(state):
            i, cents, seen, _, sse_hist, shift_hist, _, _ = state
            sums, counts, sse, cand = batch_stats(cents, i)
            seen = seen + counts
            eta = (counts / jnp.maximum(seen, 1.0))[:, None]
            bmean = sums / jnp.maximum(counts, 1.0)[:, None]
            new = jnp.where((counts > 0)[:, None],
                            (1.0 - eta) * cents + eta * bmean, cents)
            if reassignment_ratio > 0:
                do_re = ((iter0 + i + 1) % reassign_every) == 0
                new, seen = apply_reassignment(
                    new, seen, cand[0], cand[1], real, do_re,
                    reassignment_ratio, k_real, acc)
            shifts = jnp.sqrt(jnp.sum((new - cents) ** 2, axis=1))
            max_shift = jnp.max(jnp.where(real, shifts, 0.0))
            batch_w = jnp.sum(jnp.where(real, counts, 0.0))
            sse_hist = sse_hist.at[i].set(
                sse * w_total / jnp.maximum(batch_w, 1.0))
            shift_hist = shift_hist.at[i].set(max_shift)
            # All-finite flag (ISSUE 5) — see make_fit_fn: stop at the
            # diverging iteration; healthy trajectories are untouched.
            ok = jnp.all(jnp.isfinite(jnp.where(real[:, None], new, 0.0)))
            return (i + 1, new, seen, max_shift, sse_hist, shift_hist,
                    counts, ok)

        def cond(state):
            i, _, _, max_shift, _, _, _, ok = state
            return (i < max_iter) & ((i == 0) | (max_shift >= tolerance)) \
                & ok

        cents0 = lax.all_gather(cents_block, MODEL_AXIS,
                                tiled=True).astype(acc) \
            if model_shards > 1 else cents_block.astype(acc)
        seen_pad = jnp.pad(seen0.astype(acc), (0, k_pad - k_real))
        state = (jnp.int32(0), cents0, seen_pad, jnp.asarray(jnp.inf, acc),
                 jnp.zeros((max_iter,), acc), jnp.zeros((max_iter,), acc),
                 jnp.zeros((k_pad,), acc), jnp.asarray(True))
        i, cents, seen, _, sse_hist, shift_hist, counts, _ = \
            lax.while_loop(cond, body, state)
        return (cents[:k_real], seen[:k_real], i, sse_hist, shift_hist,
                counts[:k_real])

    mapped = shard_map(
        fit, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(MODEL_AXIS, None),
                  P(None), P(), P(None)),
        out_specs=(P(None, None), P(None), P(), P(None), P(None), P(None)),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_predict_fn(mesh: Mesh, *, chunk_size: int,
                    mode: str = "matmul",
                    donate_points: bool = False) -> Callable:
    """Build the jitted SPMD label assignment: (points, centroids) -> labels.

    Replaces ``predict``'s lazy per-partition closure (kmeans_spark.py:343-350)
    with an eager sharded argmin; the returned labels are sharded along the
    data axis (global indices into the un-padded centroid table).

    ``donate_points=True`` donates the points buffer to the dispatch
    (ISSUE 6: the serving engine's per-request staging buffer is
    single-use, so XLA may reuse its memory for the output) — never set
    it for a retained ``ShardedDataset``, whose points outlive the call.

    The guarded bf16 rung runs its chunk-level guard here too
    (``guarded_assign_chunk``), so ``labels_`` materialization and
    ``predict`` under ``distance_mode='matmul_bf16_guarded'`` are
    bit-equal to the f32-class labels by construction; rejected under TP
    sharding like the fit builders.

    The returned callable takes ``(points, centroids_block, n_real)``:
    ``n_real`` is the REAL (pre-padding) row count, a replicated traced
    scalar.  The guarded rung uses it to keep zero pad rows out of the
    near-tie flag — a pad row at the origin has ``d2_k ~= |c_k|^2`` and
    would fire the f32 correction cond on its chunk (the whole request,
    for single-chunk serving buckets) whenever two centroid norms are
    close; its label is sliced off by every caller, so it must never
    cost a correction pass.  The unguarded modes ignore the argument.
    """
    data_shards, model_shards = mesh_shape(mesh)
    _check_guarded(mode, model_shards)

    def predict(points, centroids_block, n_real):
        k_local, d = centroids_block.shape
        n_local = points.shape[0]
        m_idx = lax.axis_index(MODEL_AXIS) if model_shards > 1 else 0
        if mode in PALLAS_MODES:
            from kmeans_tpu.ops.pallas_kernels import pallas_assign
            interpret = jax.default_backend() != "tpu"
            bf16 = (mode == "pallas_bf16")
            if model_shards > 1:
                labels_l, mind2_l = pallas_assign(
                    points, centroids_block, bf16=bf16, interpret=interpret)
                minds = lax.all_gather(mind2_l, MODEL_AXIS)
                owner = jnp.argmin(minds, axis=0)
                contrib = jnp.where(owner == m_idx,
                                    m_idx * k_local + labels_l, 0)
                return lax.psum(contrib, MODEL_AXIS).astype(jnp.int32)
            # Assignment-only kernel: the fused variant would also run
            # the one-hot scatter matmul (same MXU FLOPs as the distance
            # matmul) only to discard the sums.
            labels, _ = pallas_assign(points, centroids_block, bf16=bf16,
                                      interpret=interpret)
            return labels
        n_chunks = n_local // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)
        if mode == GUARDED_MODE:
            # This shard's real-row count: padding is a contiguous
            # global tail, so rows at global index >= n_real are pads.
            d_idx = lax.axis_index(DATA_AXIS) if data_shards > 1 else 0
            local_real = n_real.astype(jnp.int32) - d_idx * n_local

        def body(_, chunk_in):
            xc, c_idx = chunk_in
            d2 = distance_stage(xc, centroids_block, mode=mode)
            if mode == GUARDED_MODE:
                rows = c_idx * chunk_size + jnp.arange(chunk_size,
                                                       dtype=jnp.int32)
                best_l, _ = guarded_assign_chunk(
                    xc, d2, centroids_block, valid=rows < local_real)
            else:
                best_l = jnp.argmin(d2, axis=1).astype(jnp.int32)
            if model_shards > 1:
                mind2_l = jnp.min(d2, axis=1)
                minds = lax.all_gather(mind2_l, MODEL_AXIS)
                owner = jnp.argmin(minds, axis=0)
                mine = (owner == m_idx)
                contrib = jnp.where(mine, m_idx * k_local + best_l, 0)
                best = lax.psum(contrib, MODEL_AXIS).astype(jnp.int32)
            else:
                best = best_l
            return None, best

        _, labels = lax.scan(
            body, None, (xs, jnp.arange(n_chunks, dtype=jnp.int32)))
        return labels.reshape(-1)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None), P()),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,) if donate_points else ())


@_obs_trace.traced_builder
def make_assign_margin_fn(mesh: Mesh, *, chunk_size: int,
                          mode: str = "matmul_bf16") -> Callable:
    """Guarded-assignment primitive for the serving bf16 fast path
    (ISSUE 6): (points, centroids) -> (labels, margin, scale), all
    data-sharded per row —

    * ``labels``: argmin of the (possibly quantized) distances,
    * ``margin``: second-best minus best distance (the argmin's safety
      gap),
    * ``scale``: ``|x|^2 + max_k |c_k|^2`` — the magnitude the bf16
      cross-term error is relative to (ops/assign.py: bf16 inputs
      round at ~2^-8, so the distance error is O(2^-7 * scale) and two
      distances can swap order only inside an O(2^-6 * scale) margin).

    The serving engine keeps a bf16 label only when
    ``margin > tie_rtol * scale`` (tie_rtol 2^-5 = 4x the bound) and
    recomputes the flagged near-tie rows at f32 — which is what makes
    the quantized path's labels BIT-EQUAL to the f32 oracle by
    construction instead of only on well-separated data.  Data-parallel
    meshes only (the serving engine rejects quantization under TP
    centroid sharding).
    """
    data_shards, model_shards = mesh_shape(mesh)
    if model_shards != 1:
        raise ValueError(
            "make_assign_margin_fn requires a data-parallel mesh "
            f"(model_shards == 1, got {model_shards})")

    def assign(points, centroids_block):
        k_local, d = centroids_block.shape
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)
        acc = jnp.promote_types(points.dtype, jnp.float32)
        c2max = jnp.max(jnp.sum(
            centroids_block.astype(acc) ** 2, axis=1))

        def body(_, xc):
            # Shared chunk-level error model (ops.assign.margin_chunk) —
            # the training guard (`GUARDED_MODE`) computes exactly the
            # same (best, margin, scale) triple in-graph.
            d2 = pairwise_sq_dists(xc, centroids_block, mode=mode)
            return None, margin_chunk(xc, d2, c2max)

        _, (labels, margin, scale) = lax.scan(body, None, xs)
        return (labels.reshape(-1), margin.reshape(-1),
                scale.reshape(-1))

    mapped = shard_map(
        assign, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_score_rows_fn(mesh: Mesh, *, chunk_size: int,
                       mode: str = "matmul") -> Callable:
    """Per-row squared distance to the nearest centroid:
    (points, centroids) -> mind2 (n,), data-sharded.

    The serving engine's per-request scoring primitive (ISSUE 6): a
    request's K-Means score is ``-sum`` of its rows' slice, so one
    coalesced dispatch scores every member request.  Distances come
    from the SAME ``pairwise_sq_dists`` mode ladder as assignment
    (matmul/bf16); the fused training step's SSE is the same quantity
    reduced on device, so per-request sums agree to f32 summation
    order (rtol), not bitwise.  The guarded rung maps to its f32-class
    'matmul' twin — distance VALUES are the answer here, and the
    guarded rung's value surface IS the f32 class.
    """
    mode = value_mode(mode)
    data_shards, model_shards = mesh_shape(mesh)

    def score_rows(points, centroids_block):
        k_local, d = centroids_block.shape
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)

        def body(_, xc):
            if mode in PALLAS_MODES:
                from kmeans_tpu.ops.pallas_kernels import pallas_assign
                _, mind2 = pallas_assign(
                    xc, centroids_block, bf16=(mode == "pallas_bf16"),
                    interpret=jax.default_backend() != "tpu")
            else:
                d2 = pairwise_sq_dists(xc, centroids_block, mode=mode)
                mind2 = jnp.min(d2, axis=1)
            if model_shards > 1:
                mind2 = jnp.min(lax.all_gather(mind2, MODEL_AXIS), axis=0)
            return None, mind2

        _, mind2 = lax.scan(body, None, xs)
        return mind2.reshape(-1)

    mapped = shard_map(
        score_rows, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_multi_predict_fn(mesh: Mesh, *, chunk_size: int,
                          mode: str = "matmul",
                          n_models: int) -> Callable:
    """Batched-model assignment for routed mixed-model serving batches
    (ISSUE 6): (points (n, D), centroid stack (M, k, D)) -> labels
    (M, n) — every row labeled under EVERY packed model in ONE
    dispatch; the caller selects ``labels[model_of_row, row]``.

    This is the ``make_multi_fit_fn`` restart-batching idiom applied to
    inference: the model axis is vmapped straight onto the MXU (batched
    dot_general), so a mixed batch routed across M same-shape resident
    models costs one dispatch instead of M — the M-fold distance
    compute is the price, and at serving batch sizes (<= the 4096
    bucket) it is dispatch latency, not FLOPs, that dominates.

    Data-parallel meshes only (the packed table is replicated; under TP
    centroid sharding the engine falls back to per-model dispatches).
    Pallas modes map to their matmul-form equivalents — the fused
    kernel has no batched-model variant.
    """
    data_shards, model_shards = mesh_shape(mesh)
    if model_shards != 1:
        raise ValueError(
            "make_multi_predict_fn requires a data-parallel mesh "
            f"(model_shards == 1, got {model_shards}); packed serving "
            "falls back to per-model dispatches under TP sharding")
    if mode in PALLAS_MODES:
        mode = "matmul_bf16" if mode == "pallas_bf16" else "matmul"
    # No guarded packed form (the r11 packed-quantized finding):
    # exactness wins — serve the stack at the f32 class.
    mode = value_mode(mode)

    def predict(points, cents_stack):
        d = points.shape[1]
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)

        def body(_, xc):
            def one(cb):
                d2 = pairwise_sq_dists(xc, cb, mode=mode)
                return jnp.argmin(d2, axis=1).astype(jnp.int32)
            return None, jax.vmap(one)(cents_stack)      # (M, chunk)

        _, labels = lax.scan(body, None, xs)             # (c, M, chunk)
        return jnp.moveaxis(labels, 1, 0).reshape(n_models, -1)

    mapped = shard_map(
        predict, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(None, None, None)),
        out_specs=P(None, DATA_AXIS),
        check_vma=False)
    return jax.jit(mapped)


@_obs_trace.traced_builder
def make_transform_fn(mesh: Mesh, *, chunk_size: int,
                      mode: str = "matmul") -> Callable:
    """Build the jitted SPMD distance pass for ``KMeans.transform``:
    (points, centroids) -> EUCLIDEAN distances, (n, k) sharded over BOTH
    mesh axes — no device ever materializes more than its
    (n_local, k_local) tile (r2 VERDICT weak #5: the old transform built
    the full (n, k) matrix on one device, ~41 GB at the 10M headline
    shape).  Rows scan in ``chunk_size`` tiles exactly like the training
    step; sentinel padding columns are sliced off by the caller.  The
    guarded rung maps to 'matmul' (distances are the output; its value
    surface is the f32 class — the kmeans.py serve-mode table rule)."""
    mode = value_mode(mode)
    data_shards, model_shards = mesh_shape(mesh)

    def dists(points, centroids_block):
        k_local, d = centroids_block.shape
        n_chunks = points.shape[0] // chunk_size
        xs = points.reshape(n_chunks, chunk_size, d)

        def body(_, xc):
            d2 = pairwise_sq_dists(xc, centroids_block, mode=mode)
            return None, jnp.sqrt(d2).astype(points.dtype)

        _, out = lax.scan(body, None, xs)
        return out.reshape(-1, k_local)

    mapped = shard_map(
        dists, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=P(DATA_AXIS, MODEL_AXIS),
        check_vma=False)
    return jax.jit(mapped)


def centroid_sharding(mesh: Optional[Mesh]):
    """NamedSharding for the (k_padded, D) centroid table (row-block on k)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(MODEL_AXIS, None))
