"""Distributed layer: device meshes, shardings, and the SPMD training step.

This package replaces the reference's L2 layer wholesale — the six Spark
primitives it leaned on (broadcast kmeans_spark.py:268/340, reduceByKey :169,
collect :122/173, sum :237, takeSample :72/126/196, cache/unpersist :256/317;
inventory in SURVEY.md §2.3) — with a ``jax.sharding.Mesh`` + ``shard_map``
SPMD step:

* broadcast      -> replicated sharding (maintained by XLA, free on-chip)
* reduceByKey    -> dense one-hot scatter-add + ``lax.psum`` over the data axis
* collect to driver -> disappears (the psum result is already replicated)
* rdd.sum        -> ``lax.psum`` of a scalar (fused into the same step)
* takeSample     -> seeded host-side choice over the global index space
* cache          -> arrays simply stay device-resident across iterations

Mesh axes: ``data`` shards the N points (the DP axis — the reference's only
parallelism, partition count at kmeans_spark.py:418/568); the optional
``model`` axis shards the (k, D) centroid table for large k*D (the TP/EP
analogue, a capability the reference lacks — SURVEY.md §2.3).
"""

from kmeans_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from kmeans_tpu.parallel.sharding import (INGEST_MODES, ShardedDataset,
                                          check_ingest, pad_points,
                                          resolve_ingest, shard_points,
                                          to_device)
from kmeans_tpu.parallel.distributed import make_step_fn, make_predict_fn

__all__ = [
    "DATA_AXIS",
    "INGEST_MODES",
    "MODEL_AXIS",
    "ShardedDataset",
    "check_ingest",
    "make_mesh",
    "make_step_fn",
    "make_predict_fn",
    "pad_points",
    "resolve_ingest",
    "shard_points",
    "to_device",
]
