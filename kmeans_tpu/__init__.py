"""kmeans_tpu — a TPU-native distributed K-Means framework.

A ground-up JAX/XLA re-design of the capabilities of the PySpark reference
implementation ``ersanjay16/Assignment--2-Group7-distributed-K-means``
(``kmeans_spark.py``): the per-partition nearest-centroid assignment and the
``reduceByKey`` centroid/SSE aggregation become a single jit-compiled
pairwise-distance + one-hot scatter-sum step, and the Spark driver's
broadcast/shuffle/collect loop becomes a ``jax.lax.psum`` over a TPU device
mesh (``jax.sharding.Mesh`` + ``jax.shard_map``).

Public API (capability parity with the reference's face-sheet "KEY API",
``kmeans_spark.py:37-47`` — ``KMeans(k, max_iter, tolerance, seed,
compute_sse)`` with ``.fit`` / ``.predict`` / ``.centroids`` /
``.sse_history``), plus TPU-native extensions (meshes, dtype control,
kmeans++ init, checkpointing, profiling).
"""

from kmeans_tpu.models.kmeans import DispatchLatencyHint, KMeans
from kmeans_tpu.models.minibatch import MiniBatchKMeans
from kmeans_tpu.models.bisecting import BisectingKMeans
from kmeans_tpu.models.spherical import SphericalKMeans
from kmeans_tpu.models.gmm import GaussianMixture
from kmeans_tpu.models.fault_tolerance import NumericalDivergenceError
from kmeans_tpu.models.pq import ProductQuantizer
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.parallel.sharding import ShardedDataset
from kmeans_tpu.sweep import SweepResult

__version__ = "0.1.0"

__all__ = ["KMeans", "MiniBatchKMeans", "BisectingKMeans",
           "SphericalKMeans", "GaussianMixture", "DispatchLatencyHint",
           "NumericalDivergenceError", "ProductQuantizer", "ShardedDataset",
           "SweepResult", "make_mesh", "__version__"]
